"""Tests for the tensor-op / scalar / sparse / locally-connected layers
added for layer-inventory parity (reference keras/layers/*.scala) — oracle
comparisons against torch or numpy per SURVEY.md §4."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.test_layers import apply_layer  # noqa: E402

rng0 = np.random.default_rng(0)


def test_scalar_ops():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AddConstant, Exp, Log, MulConstant, Negative, Power, Sqrt, Square,
    )

    x = rng0.uniform(0.5, 2.0, size=(3, 4)).astype(np.float32)
    for layer, fn in [
        (AddConstant(2.5), lambda v: v + 2.5),
        (MulConstant(-3.0), lambda v: v * -3.0),
        (Negative(), lambda v: -v),
        (Power(2.0, scale=1.5, shift=0.25), lambda v: (0.25 + 1.5 * v) ** 2),
        (Sqrt(), np.sqrt),
        (Square(), np.square),
        (Exp(), np.exp),
        (Log(), np.log),
    ]:
        out, _ = apply_layer(layer, x)
        np.testing.assert_allclose(out, fn(x), rtol=1e-5, atol=1e-6)


def test_threshold_family_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        HardShrink, HardTanh, SoftShrink, Softmax, Threshold,
    )

    x = rng0.normal(size=(4, 6)).astype(np.float32)
    t = torch.from_numpy(x)

    out, _ = apply_layer(HardShrink(0.4), x)
    np.testing.assert_allclose(out, torch.nn.Hardshrink(0.4)(t), atol=1e-6)

    out, _ = apply_layer(SoftShrink(0.4), x)
    np.testing.assert_allclose(out, torch.nn.Softshrink(0.4)(t), atol=1e-6)

    out, _ = apply_layer(HardTanh(-0.5, 0.7), x)
    np.testing.assert_allclose(
        out, torch.nn.Hardtanh(-0.5, 0.7)(t), atol=1e-6
    )

    out, _ = apply_layer(Threshold(0.1, v=-1.0), x)
    np.testing.assert_allclose(
        out, torch.nn.Threshold(0.1, -1.0)(t), atol=1e-6
    )

    out, _ = apply_layer(Softmax(), x)
    np.testing.assert_allclose(
        out, torch.softmax(t, dim=-1), rtol=1e-5, atol=1e-6
    )


def test_binary_threshold_and_rrelu():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        BinaryThreshold, RReLU,
    )

    x = rng0.normal(size=(3, 5)).astype(np.float32)
    out, _ = apply_layer(BinaryThreshold(0.0), x)
    np.testing.assert_array_equal(out, (x > 0).astype(np.float32))

    # eval: fixed mean slope
    out, _ = apply_layer(RReLU(0.25, 0.75), x)
    ref = np.where(x >= 0, x, 0.5 * x)
    np.testing.assert_allclose(out, ref, atol=1e-6)

    # train: slopes within [lower, upper]
    layer = RReLU(0.25, 0.75)
    out, _ = apply_layer(layer, x, rng=jax.random.PRNGKey(1), training=True)
    neg = x < 0
    slopes = np.asarray(out)[neg] / x[neg]
    assert np.all(slopes >= 0.25 - 1e-6) and np.all(slopes <= 0.75 + 1e-6)
    np.testing.assert_allclose(np.asarray(out)[~neg], x[~neg])


def test_learnable_affine_ops():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        CAdd, CMul, Mul, Scale,
    )

    x = rng0.normal(size=(2, 3, 4)).astype(np.float32)

    layer = CAdd((1, 4))
    out, params = apply_layer(layer, x)
    np.testing.assert_allclose(out, x + np.asarray(params["bias"]),
                               atol=1e-6)

    layer = CMul((3, 1))
    out, params = apply_layer(layer, x)
    np.testing.assert_allclose(out, x * np.asarray(params["weight"]),
                               atol=1e-6)

    layer = Scale((3, 4))
    out, params = apply_layer(layer, x)
    np.testing.assert_allclose(
        out, x * np.asarray(params["weight"]) + np.asarray(params["bias"]),
        atol=1e-6,
    )

    layer = Mul()
    out, params = apply_layer(layer, x)
    np.testing.assert_allclose(out, x * np.asarray(params["weight"]),
                               atol=1e-6)


def test_shape_and_table_ops():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Expand, GetShape, Max, Narrow, SelectTable, SplitTensor,
    )

    x = rng0.normal(size=(2, 3, 4)).astype(np.float32)

    out, _ = apply_layer(GetShape(), x)
    np.testing.assert_array_equal(out, [2, 3, 4])

    small = x[:, :1, :]
    layer = Expand((3, 4))
    layer.ensure_built((1, 4))
    out, _ = layer.apply({}, jnp.asarray(small))
    np.testing.assert_allclose(out, np.broadcast_to(small, (2, 3, 4)))

    out, _ = apply_layer(Narrow(1, 1, 2), x)
    np.testing.assert_allclose(out, x[:, 1:3])
    assert Narrow(2, 1, -1).compute_output_shape((2, 3, 4)) == (2, 3, 3)

    out, _ = apply_layer(Max(2), x)
    np.testing.assert_allclose(out, x.max(axis=2), rtol=1e-6)
    assert Max(1, keep_dim=True).compute_output_shape((2, 3, 4)) == (2, 1, 4)

    xs = [x, 2 * x]
    layer = SelectTable(1)
    out = layer.call({}, xs)
    np.testing.assert_allclose(out, 2 * x)

    layer = SplitTensor(2, 2)
    parts = layer.call({}, jnp.asarray(x))
    assert len(parts) == 2
    np.testing.assert_allclose(parts[0], x[:, :, :2])
    np.testing.assert_allclose(parts[1], x[:, :, 2:])


def test_gaussian_sampler():
    from analytics_zoo_tpu.pipeline.api.keras.layers import GaussianSampler

    mean = rng0.normal(size=(4, 8)).astype(np.float32)
    log_var = np.full((4, 8), -2.0, dtype=np.float32)
    layer = GaussianSampler()

    out = layer.call({}, [jnp.asarray(mean), jnp.asarray(log_var)])
    np.testing.assert_allclose(out, mean)  # inference = mean

    out = layer.call({}, [jnp.asarray(mean), jnp.asarray(log_var)],
                     training=True, rng=jax.random.PRNGKey(0))
    std = np.exp(-1.0)
    diff = np.asarray(out) - mean
    assert np.abs(diff).max() < 6 * std
    assert np.abs(diff).max() > 0


def test_lrn2d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import LRN2D

    x = rng0.normal(size=(2, 5, 5, 6)).astype(np.float32)
    layer = LRN2D(alpha=1e-3, k=2.0, beta=0.75, n=5)
    out, _ = apply_layer(layer, x)

    ref = torch.nn.LocalResponseNorm(5, alpha=1e-3, beta=0.75, k=2.0)(
        torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
    ).numpy()
    np.testing.assert_allclose(
        out, np.transpose(ref, (0, 2, 3, 1)), rtol=1e-4, atol=1e-5
    )


def _tf1_resize_bilinear_oracle(x, out_h, out_w):
    """Independent numpy implementation of TF1 resize_bilinear with
    align_corners=False: src = dst * in/out (ASYMMETRIC — torch/cv2 use
    half-pixel, which gives different numbers; round-1 advisor finding)."""
    b, h, w, c = x.shape
    out = np.empty((b, out_h, out_w, c), np.float32)
    for i in range(out_h):
        sy = min(i * h / out_h, h - 1)
        y0, wy = int(np.floor(sy)), sy - int(np.floor(sy))
        y1 = min(y0 + 1, h - 1)
        for j in range(out_w):
            sx = min(j * w / out_w, w - 1)
            x0, wx = int(np.floor(sx)), sx - int(np.floor(sx))
            x1 = min(x0 + 1, w - 1)
            top = x[:, y0, x0] * (1 - wx) + x[:, y0, x1] * wx
            bot = x[:, y1, x0] * (1 - wx) + x[:, y1, x1] * wx
            out[:, i, j] = top * (1 - wy) + bot * wy
    return out


def test_resize_bilinear_tf1_asymmetric_oracle():
    from analytics_zoo_tpu.pipeline.api.keras.layers import ResizeBilinear

    x = rng0.normal(size=(2, 6, 8, 3)).astype(np.float32)
    for out_h, out_w in [(3, 4), (11, 5), (6, 8)]:
        out, _ = apply_layer(ResizeBilinear(out_h, out_w), x)
        ref = _tf1_resize_bilinear_oracle(x, out_h, out_w)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_resize_bilinear_align_corners_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import ResizeBilinear

    x = rng0.normal(size=(2, 6, 8, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
    # align_corners=True is the one convention torch and TF1 share
    out, _ = apply_layer(ResizeBilinear(11, 5, align_corners=True), x)
    ref = torch.nn.functional.interpolate(
        t, size=(11, 5), mode="bilinear", align_corners=True
    ).numpy()
    np.testing.assert_allclose(
        out, np.transpose(ref, (0, 2, 3, 1)), rtol=1e-4, atol=1e-5
    )


def test_maxout_dense():
    from analytics_zoo_tpu.pipeline.api.keras.layers import MaxoutDense

    x = rng0.normal(size=(5, 7)).astype(np.float32)
    layer = MaxoutDense(3, nb_feature=4)
    out, params = apply_layer(layer, x)

    w = np.asarray(params["kernel"]).reshape(7, 4, 3)
    b = np.asarray(params["bias"]).reshape(4, 3)
    ref = np.max(np.einsum("bi,iko->bko", x, w) + b, axis=1)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    assert layer.compute_output_shape((None, 7)) == (None, 3)


def test_sparse_dense_matches_dense():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SparseDense

    dense = np.zeros((3, 6), dtype=np.float32)
    coords = [(0, 1, 2.0), (0, 4, -1.0), (1, 0, 3.0), (2, 5, 0.5)]
    for r, c, v in coords:
        dense[r, c] = v
    indices = np.asarray([(r, c) for r, c, _ in coords], dtype=np.int32)
    values = np.asarray([v for _, _, v in coords], dtype=np.float32)

    layer = SparseDense(4, activation="relu")
    out_dense, params = apply_layer(layer, dense)
    out_sparse = layer.call(
        params, (jnp.asarray(indices), jnp.asarray(values), (3, 6))
    )
    np.testing.assert_allclose(out_sparse, out_dense, rtol=1e-5, atol=1e-6)


def test_word_embedding(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        SparseEmbedding, WordEmbedding,
    )

    glove = tmp_path / "glove.txt"
    glove.write_text(
        "hello 0.1 0.2 0.3\nworld 1.0 -1.0 0.5\nzoo 0.0 0.0 1.0\n"
    )
    word_index = {"hello": 1, "world": 2, "zoo": 3}
    layer = WordEmbedding(str(glove), word_index, input_length=4)
    assert layer.n_pretrained == 3

    ids = np.asarray([[1, 2, 3, 0]], dtype=np.int32)
    out, params = apply_layer(layer, ids)
    np.testing.assert_allclose(out[0, 0], [0.1, 0.2, 0.3], atol=1e-6)
    np.testing.assert_allclose(out[0, 1], [1.0, -1.0, 0.5], atol=1e-6)
    np.testing.assert_allclose(out[0, 3], [0.0, 0.0, 0.0], atol=1e-6)
    # frozen: the table lives in (non-trainable) state, not params
    assert not params
    assert layer._state_specs[0].name == "embeddings"

    idx = WordEmbedding.get_word_index(str(glove))
    assert set(idx) == {"hello", "world", "zoo"}

    se = SparseEmbedding(5, 3)
    out, _ = apply_layer(se, np.asarray([[0, 4]], dtype=np.int32))
    assert out.shape == (1, 2, 3)


def test_locally_connected_2d_vs_manual():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        LocallyConnected2D,
    )

    x = rng0.normal(size=(2, 5, 6, 3)).astype(np.float32)
    layer = LocallyConnected2D(4, 2, 3, subsample=(1, 2))
    out, params = apply_layer(layer, x)
    assert out.shape == (2, 4, 2, 4)

    w = np.asarray(params["kernel"])
    b = np.asarray(params["bias"])
    for i in range(4):
        for j in range(2):
            patch = x[:, i:i + 2, j * 2:j * 2 + 3, :].reshape(2, -1)
            ref = patch @ w[i, j] + b[i, j]
            np.testing.assert_allclose(out[:, i, j], ref, rtol=1e-4,
                                       atol=1e-5)


def test_share_convolution2d_matches_padded_conv():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        ShareConvolution2D,
    )

    x = rng0.normal(size=(2, 7, 7, 3)).astype(np.float32)
    layer = ShareConvolution2D(4, 3, 3, pad_h=1, pad_w=1)
    out, params = apply_layer(layer, x)

    conv = torch.nn.Conv2d(3, 4, 3, padding=1)
    with torch.no_grad():
        w = np.transpose(np.asarray(params["kernel"]), (3, 2, 0, 1))
        conv.weight.copy_(torch.from_numpy(w))
        conv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = conv(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))).numpy()
    np.testing.assert_allclose(
        out, np.transpose(ref, (0, 2, 3, 1)), rtol=1e-4, atol=1e-5
    )
    assert layer.compute_output_shape((2, 7, 7, 3)) == (2, 7, 7, 4)


def test_conv_lstm_3d_shapes():
    from analytics_zoo_tpu.pipeline.api.keras.layers import ConvLSTM3D

    x = rng0.normal(size=(2, 3, 4, 5, 6, 2)).astype(np.float32)
    layer = ConvLSTM3D(3, 2, return_sequences=True)
    out, _ = apply_layer(layer, x)
    assert out.shape == (2, 3, 4, 5, 6, 3)

    layer = ConvLSTM3D(3, 2, return_sequences=False, subsample=(2, 2, 2))
    out, _ = apply_layer(layer, x)
    assert out.shape == (2, 2, 3, 3, 3)


def test_spatial_dropout3d():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SpatialDropout3D

    x = np.ones((2, 3, 4, 5, 6), dtype=np.float32)
    layer = SpatialDropout3D(0.5)
    out, _ = apply_layer(layer, x, rng=jax.random.PRNGKey(3), training=True)
    out = np.asarray(out)
    # each (sample, channel) map is uniformly kept (scaled) or dropped
    per_map = out.reshape(2, -1, 6)
    for s in range(2):
        for c in range(6):
            vals = np.unique(per_map[s, :, c])
            assert len(vals) == 1 and vals[0] in (0.0, 2.0)


def test_word_embedding_robust_parsing(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras.layers import WordEmbedding

    f = tmp_path / "vecs.txt"
    # word2vec header + multi-token word + normal lines
    f.write_text(
        "3 3\n. . . 0.9 0.8 0.7\nhello 0.1 0.2 0.3\nworld 1.0 -1.0 0.5\n"
    )
    vectors, dim = WordEmbedding._load_vectors(str(f))
    assert dim == 3
    assert set(vectors) == {". . .", "hello", "world"}
    np.testing.assert_allclose(vectors[". . ."], [0.9, 0.8, 0.7])


def test_sparse_dense_backward_window():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SparseDense

    x = rng0.normal(size=(2, 6)).astype(np.float32)
    layer = SparseDense(3, backward_start=2, backward_length=3)
    layer.ensure_built((6,))
    params = layer.init_params(jax.random.PRNGKey(0))

    def loss(xx):
        return jnp.sum(layer.call(params, xx) ** 2)

    g = np.asarray(jax.grad(loss)(jnp.asarray(x)))
    # grads only inside 1-based window [2, 4] -> 0-based cols 1..3
    assert np.all(g[:, [0, 4, 5]] == 0)
    assert np.any(g[:, 1:4] != 0)

    # COO path: same window masking on values
    indices = np.asarray([[0, 0], [0, 2], [1, 3], [1, 5]], dtype=np.int32)
    values = np.asarray([1.0, 2.0, 3.0, 4.0], dtype=np.float32)

    def loss_coo(v):
        return jnp.sum(
            layer.call(params, (jnp.asarray(indices), v, (2, 6))) ** 2
        )

    gv = np.asarray(jax.grad(loss_coo)(jnp.asarray(values)))
    assert gv[0] == 0 and gv[3] == 0
    assert gv[1] != 0 and gv[2] != 0


def test_word_embedding_dim_inference_poison_resistant(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras.layers import WordEmbedding

    f = tmp_path / "poison.txt"
    # first data line's word ends in a float-parseable token ("win 7"),
    # inflating its float-suffix length; dim must still come out as 3
    lines = ["win 7 0.1 0.2 0.3"]
    lines += [f"w{i} {i}.0 {i}.5 {i}.25" for i in range(12)]
    f.write_text("\n".join(lines) + "\n")
    vectors, dim = WordEmbedding._load_vectors(str(f))
    assert dim == 3
    assert "win 7" in vectors and len(vectors) == 13
    np.testing.assert_allclose(vectors["win 7"], [0.1, 0.2, 0.3])
    # parse cache: same (path, mtime) returns the identical object
    again, _ = WordEmbedding._load_vectors(str(f))
    assert again is vectors


def test_sparse_dense_traced_dense_shape_raises():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SparseDense

    layer = SparseDense(3)
    layer.ensure_built((6,))
    params = layer.init_params(jax.random.PRNGKey(0))
    indices = jnp.asarray([[0, 0]], dtype=jnp.int32)
    values = jnp.asarray([1.0], dtype=jnp.float32)

    @jax.jit
    def f(shape_arr):
        return layer.call(params, (indices, values, shape_arr))

    with pytest.raises(TypeError, match="static"):
        f(jnp.asarray([2, 6]))


def test_lrn2d_even_n_caffe_window():
    from analytics_zoo_tpu.pipeline.api.keras.layers import LRN2D

    x = rng0.normal(size=(1, 2, 2, 6)).astype(np.float32)
    layer = LRN2D(alpha=0.1, k=1.0, beta=0.5, n=4)
    out, _ = apply_layer(layer, x)

    # caffe/BigDL convention: window for channel i is [i-(n-1)//2, i+n//2]
    ref = np.empty_like(x)
    for c in range(6):
        lo, hi = max(0, c - 1), min(6, c + 3)
        s = (x[..., lo:hi] ** 2).sum(-1)
        ref[..., c] = x[..., c] / (1.0 + 0.1 / 4 * s) ** 0.5
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_sparse_dense_rejects_zero_backward_start():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SparseDense

    with pytest.raises(ValueError, match="1-based"):
        SparseDense(3, backward_start=0)


def test_config_roundtrip_args_recorded():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        ResizeBilinear, ShareConvolution2D,
    )

    cfg = ResizeBilinear(11, 5, align_corners=True).get_config()
    assert cfg["align_corners"] is True
    cfg = ShareConvolution2D(4, 3, 3, pad_h=1, pad_w=2,
                             propagate_back=False).get_config()
    assert cfg["pad_h"] == 1 and cfg["pad_w"] == 2
    assert cfg["propagate_back"] is False


def test_resize_bilinear_align_corners_per_axis():
    from analytics_zoo_tpu.pipeline.api.keras.layers import ResizeBilinear

    # out_w == 1 must not drag the h-axis off the align_corners mapping:
    # rows sampled at [0, 2, 4] for in_h=5 -> exact input rows
    x = np.arange(5, dtype=np.float32)[None, :, None, None] * np.ones(
        (1, 5, 3, 1), np.float32)
    out, _ = apply_layer(ResizeBilinear(3, 1, align_corners=True), x)
    np.testing.assert_allclose(np.asarray(out)[0, :, 0, 0], [0.0, 2.0, 4.0])


def test_space_to_depth_vs_tf_order_oracle():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SpaceToDepth

    x = rng0.normal(size=(2, 4, 6, 3)).astype(np.float32)
    out, _ = apply_layer(SpaceToDepth(2), x)
    assert out.shape == (2, 2, 3, 12)
    # TF channel order: output[b, i, j, (di*blk + dj)*C + c]
    ref = np.zeros((2, 2, 3, 12), np.float32)
    for di in range(2):
        for dj in range(2):
            for c in range(3):
                ref[..., (di * 2 + dj) * 3 + c] = x[:, di::2, dj::2, c]
    np.testing.assert_array_equal(out, ref)
    with pytest.raises(ValueError, match="not divisible"):
        SpaceToDepth(2).compute_output_shape((1, 5, 6, 3))


def test_space_to_depth_stem_equals_strided_conv():
    """4x4/s1 conv on the s2d grid == 8x8/s2 conv on the original image
    (kernel rearranged): the stem reformulation is exact, not approximate."""
    import jax.numpy as jnp
    from jax import lax

    x = rng0.normal(size=(1, 16, 16, 3)).astype(np.float32)
    k8 = rng0.normal(size=(8, 8, 3, 5)).astype(np.float32)
    ref = lax.conv_general_dilated(
        x, k8, window_strides=(2, 2), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # rearrange (8,8,3,5) -> (4,4,12,5): tap (2i+di, 2j+dj, c) goes to
    # spatial (i, j), input channel (di*2+dj)*3+c  (TF s2d order)
    k4 = np.zeros((4, 4, 12, 5), np.float32)
    for di in range(2):
        for dj in range(2):
            for c in range(3):
                k4[:, :, (di * 2 + dj) * 3 + c] = k8[di::2, dj::2, c]
    from analytics_zoo_tpu.pipeline.api.keras.layers import SpaceToDepth

    xs, _ = apply_layer(SpaceToDepth(2), x)
    out = lax.conv_general_dilated(
        np.asarray(xs), k4, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
