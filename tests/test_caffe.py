"""Caffe loader tests (reference CaffeLoaderSpec / models/caffe converters).

caffemodel binaries are fabricated with the shared protobuf wire writer;
layer math is oracle-checked against torch functional ops.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.caffe import (
    CaffeNet, load_caffe, parse_caffemodel, parse_prototxt,
)
from analytics_zoo_tpu.pipeline.api.onnx.proto import (
    _put_bytes, _put_varint,
)

rng0 = np.random.default_rng(0)


# -- caffemodel fabrication -------------------------------------------------

def encode_blob(arr):
    out = bytearray()
    shape = bytearray()
    for d in arr.shape:
        _put_varint(shape, 1, d)
    _put_bytes(out, 7, bytes(shape))
    _put_bytes(out, 5, np.ascontiguousarray(
        arr, dtype=np.float32).tobytes())
    return bytes(out)


def encode_caffemodel(layer_blobs):
    """layer_blobs: {layer_name: [np arrays]} → NetParameter bytes."""
    out = bytearray()
    _put_bytes(out, 1, b"net")
    for name, blobs in layer_blobs.items():
        layer = bytearray()
        _put_bytes(layer, 1, name.encode())
        _put_bytes(layer, 2, b"Convolution")  # type (unused by parser)
        for arr in blobs:
            _put_bytes(layer, 7, encode_blob(arr))
        _put_bytes(out, 100, bytes(layer))
    return bytes(out)


PROTOTXT = """
name: "TestNet"  # a comment
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 }
}
layer {
  name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1"
  batch_norm_param { eps: 1e-5 }
}
layer {
  name: "scale1" type: "Scale" bottom: "bn1" top: "scale1"
  scale_param { bias_term: true }
}
layer { name: "relu1" type: "ReLU" bottom: "scale1" top: "scale1" }
layer {
  name: "pool1" type: "Pooling" bottom: "scale1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def test_parse_prototxt():
    net = parse_prototxt(PROTOTXT)
    assert net["name"] == "TestNet"
    assert net["input"] == "data"
    assert net["input_shape"]["dim"] == [1, 3, 8, 8]
    layers = net["layer"]
    assert [ly["type"] for ly in layers] == [
        "Convolution", "BatchNorm", "Scale", "ReLU", "Pooling",
        "InnerProduct", "Softmax",
    ]
    assert layers[0]["convolution_param"]["num_output"] == 4
    assert layers[4]["pooling_param"]["pool"] == "MAX"


def test_caffemodel_roundtrip():
    w = rng0.normal(size=(4, 3, 3, 3)).astype(np.float32)
    b = rng0.normal(size=(4,)).astype(np.float32)
    data = encode_caffemodel({"conv1": [w, b]})
    blobs = parse_caffemodel(data)
    assert set(blobs) == {"conv1"}
    np.testing.assert_allclose(blobs["conv1"][0], w)
    np.testing.assert_allclose(blobs["conv1"][1], b)


def _make_blobs():
    w = (rng0.normal(size=(4, 3, 3, 3)) * 0.3).astype(np.float32)
    b = rng0.normal(size=(4,)).astype(np.float32)
    mean = (rng0.normal(size=(4,)) * 0.1).astype(np.float32)
    var = rng0.uniform(0.5, 1.5, size=(4,)).astype(np.float32)
    factor = np.asarray([1.0], dtype=np.float32)
    gamma = rng0.uniform(0.5, 1.5, size=(4,)).astype(np.float32)
    beta = rng0.normal(size=(4,)).astype(np.float32)
    fcw = (rng0.normal(size=(5, 4 * 4 * 4)) * 0.1).astype(np.float32)
    fcb = rng0.normal(size=(5,)).astype(np.float32)
    return {
        "conv1": [w, b],
        "bn1": [mean, var, factor],
        "scale1": [gamma, beta],
        "fc": [fcw, fcb],
    }


def test_caffe_net_vs_torch(tmp_path):
    import torch
    import torch.nn.functional as F

    blobs = _make_blobs()
    proto = tmp_path / "net.prototxt"
    proto.write_text(PROTOTXT)
    model = tmp_path / "net.caffemodel"
    model.write_bytes(encode_caffemodel(blobs))

    net = load_caffe(str(proto), str(model))
    net.ensure_built((3, 8, 8))
    params = net.init_params(jax.random.PRNGKey(0))
    x = rng0.normal(size=(2, 3, 8, 8)).astype(np.float32)
    out, _ = net.apply(params, jnp.asarray(x))

    t = torch.from_numpy
    y = F.conv2d(t(x), t(blobs["conv1"][0]), t(blobs["conv1"][1]),
                 padding=1)
    y = (y - t(blobs["bn1"][0]).view(1, -1, 1, 1)) \
        / torch.sqrt(t(blobs["bn1"][1]).view(1, -1, 1, 1) + 1e-5)
    y = y * t(blobs["scale1"][0]).view(1, -1, 1, 1) \
        + t(blobs["scale1"][1]).view(1, -1, 1, 1)
    y = F.max_pool2d(torch.relu(y), 2, 2)
    y = y.flatten(1) @ t(blobs["fc"][0]).T + t(blobs["fc"][1])
    ref = torch.softmax(y, dim=1).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                               atol=1e-5)
    # weights became trainable params
    assert any(k.startswith("conv1/") for k in params)


def test_caffe_pooling_ceil_rounding():
    import torch
    import torch.nn.functional as F

    # caffe pools round UP: 7 -> ceil((7-3)/2)+1 = 3 (torch default floors)
    proto = """
input: "data"
input_shape { dim: 1 dim: 1 dim: 7 dim: 7 }
layer { name: "p" type: "Pooling" bottom: "data" top: "p"
        pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((1, 7, 7))
    x = rng0.normal(size=(1, 1, 7, 7)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    ref = F.max_pool2d(torch.from_numpy(x), 3, 2, ceil_mode=True).numpy()
    assert np.asarray(out).shape == ref.shape == (1, 1, 3, 3)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_caffe_eltwise_concat_split():
    proto = """
input: "data"
input_shape { dim: 1 dim: 2 dim: 4 dim: 4 }
layer { name: "split" type: "Split" bottom: "data" top: "a" top: "b" }
layer { name: "sum" type: "Eltwise" bottom: "a" bottom: "b" top: "s"
        eltwise_param { operation: SUM coeff: 1.0 coeff: 2.0 } }
layer { name: "cat" type: "Concat" bottom: "s" bottom: "a" top: "c" }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((2, 4, 4))
    x = rng0.normal(size=(1, 2, 4, 4)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    ref = np.concatenate([3 * x, x], axis=1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-6)


def test_caffe_train_only_layers_dropped():
    proto = """
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "flat" type: "Flatten" bottom: "data" top: "flat" }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "flat" top: "loss" }
layer { name: "drop" type: "Dropout" bottom: "flat" top: "flat"
        include { phase: TRAIN } }
"""
    net = CaffeNet(parse_prototxt(proto))
    assert [str(l["type"]) for l in net.layers] == ["Flatten"]


def test_caffe_unsupported_type_raises():
    proto = """
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "x" type: "SPPLayer" bottom: "data" top: "x" }
"""
    with pytest.raises(NotImplementedError, match="SPPLayer"):
        CaffeNet(parse_prototxt(proto))


def test_net_facade_load_caffe(tmp_path):
    from analytics_zoo_tpu.pipeline.api.net import Net

    proto = tmp_path / "n.prototxt"
    proto.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "p" type: "Power" bottom: "data" top: "p"
        power_param { power: 2.0 scale: 1.0 shift: 0.0 } }
""")
    net = Net.load_caffe(str(proto))
    net.ensure_built((1, 4, 4))
    x = rng0.normal(size=(1, 1, 4, 4)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x ** 2, rtol=1e-5,
                               atol=1e-6)


def test_caffe_net_finetunes():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential

    rng = np.random.default_rng(11)
    proto = """
input: "data"
input_shape { dim: 1 dim: 8 }
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
        inner_product_param { num_output: 2 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""
    blobs = {"fc": [
        (rng.normal(size=(2, 8)) * 0.3).astype(np.float32),
        np.zeros(2, dtype=np.float32),
    ]}
    net = CaffeNet(parse_prototxt(proto), blobs)
    net._input_shape = (8,)
    m = Sequential()
    m.add(net)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int64)
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=32, nb_epoch=250)
    res = m.evaluate(x, y, batch_size=32)
    assert res["accuracy"] > 0.85, res


def test_caffe_missing_weights_raises_value_error():
    # Round-1 advisor finding (b): no .caffemodel used to crash deep in lax
    proto = """
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "c" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 4 kernel_size: 3 } }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((3, 8, 8))
    params = net.init_params(__import__("jax").random.PRNGKey(0))
    with pytest.raises(ValueError, match="model_path"):
        net.apply(params, jnp.zeros((1, 3, 8, 8), jnp.float32))


def test_caffe_lrn_within_channel_oracle():
    # Round-1 advisor finding (c): norm_region was ignored
    proto = """
input: "data"
input_shape { dim: 1 dim: 2 dim: 6 dim: 6 }
layer { name: "l" type: "LRN" bottom: "data" top: "l"
        lrn_param { local_size: 3 alpha: 2.0 beta: 0.5
                    norm_region: WITHIN_CHANNEL } }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((2, 6, 6))
    x = rng0.normal(size=(1, 2, 6, 6)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    # independent numpy oracle: per-channel 3x3 spatial window
    sq = np.pad(x ** 2, ((0, 0), (0, 0), (1, 1), (1, 1)))
    win = sum(sq[:, :, i:i + 6, j:j + 6]
              for i in range(3) for j in range(3))
    expect = x / np.power(1.0 + 2.0 / 9.0 * win, 0.5)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                               atol=1e-6)


def test_caffe_lrn_across_channels_still_default():
    proto = """
input: "data"
input_shape { dim: 1 dim: 4 dim: 2 dim: 2 }
layer { name: "l" type: "LRN" bottom: "data" top: "l"
        lrn_param { local_size: 3 alpha: 1.0 beta: 0.75 } }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((4, 2, 2))
    x = rng0.normal(size=(1, 4, 2, 2)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    sq = np.pad(x ** 2, ((0, 0), (1, 1), (0, 0), (0, 0)))
    win = sum(sq[:, i:i + 4] for i in range(3))
    expect = x / np.power(1.0 + 1.0 / 3.0 * win, 0.75)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                               atol=1e-6)


def test_caffe_stochastic_pooling_rejected():
    # Round-1 advisor finding (d): STOCHASTIC silently executed as AVE
    proto = """
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "p" type: "Pooling" bottom: "data" top: "p"
        pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 } }
"""
    net = CaffeNet(parse_prototxt(proto))
    net.ensure_built((1, 4, 4))
    with pytest.raises(NotImplementedError, match="STOCHASTIC"):
        net.apply({}, jnp.zeros((1, 1, 4, 4), jnp.float32))


# ---------------------------------------------------------------------------
# V1 (upgrade_proto-era) format — reference V1LayerConverter.scala:39
# ---------------------------------------------------------------------------

V1_PROTOTXT = """
name: "V1Net"
input: "data"
input_dim: 1
input_dim: 3
input_dim: 8
input_dim: 8
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layers {
  name: "conv1"
  type: CONVOLUTION
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers {
  name: "pool1"
  type: POOLING
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layers { name: "flat" type: FLATTEN bottom: "pool1" top: "flat" }
layers {
  name: "fc"
  type: INNER_PRODUCT
  bottom: "flat"
  top: "fc"
  inner_product_param { num_output: 5 }
}
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "fc" top: "loss" }
layers { name: "acc" type: ACCURACY bottom: "prob" top: "acc" }
"""


def encode_v1_caffemodel(layer_blobs, type_enum=4):
    """V1 NetParameter: repeated V1LayerParameter `layers` = field 2
    (name=4, type=5 enum, blobs=6)."""
    out = bytearray()
    _put_bytes(out, 1, b"v1net")
    for name, blobs in layer_blobs.items():
        layer = bytearray()
        _put_bytes(layer, 4, name.encode())
        _put_varint(layer, 5, type_enum)
        for arr in blobs:
            _put_bytes(layer, 6, encode_blob(arr))
        _put_bytes(out, 2, bytes(layer))
    return bytes(out)


def test_caffe_v1_net_vs_torch(tmp_path):
    """A V1-format (enum-typed `layers`) prototxt + V1 binary caffemodel
    loads and matches torch — the legacy path CaffeLoader.scala:63-671
    serves via V1LayerConverter."""
    import torch
    import torch.nn.functional as F

    w = (rng0.normal(size=(4, 3, 3, 3)) * 0.3).astype(np.float32)
    b = rng0.normal(size=(4,)).astype(np.float32)
    fcw = (rng0.normal(size=(5, 4 * 4 * 4)) * 0.1).astype(np.float32)
    fcb = rng0.normal(size=(5,)).astype(np.float32)
    blobs = {"conv1": [w, b], "fc": [fcw, fcb]}

    proto = tmp_path / "v1.prototxt"
    proto.write_text(V1_PROTOTXT)
    model = tmp_path / "v1.caffemodel"
    model.write_bytes(encode_v1_caffemodel(blobs))

    net = load_caffe(str(proto), str(model))
    net.ensure_built((3, 8, 8))
    params = net.init_params(jax.random.PRNGKey(0))
    x = rng0.normal(size=(2, 3, 8, 8)).astype(np.float32)
    out, _ = net.apply(params, jnp.asarray(x))

    t = torch.from_numpy
    y = F.conv2d(t(x), t(w), t(b), padding=1)
    y = F.max_pool2d(torch.relu(y), 2, 2)
    y = y.flatten(1) @ t(fcw).T + t(fcb)
    ref = torch.softmax(y, dim=1).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    # V1 loss/accuracy heads were dropped; prob is the only output
    assert net.output_names == ["prob"]


def test_caffe_v1_int_enum_types():
    """Binary-parsed V1 nets carry int enum types; normalize_v1_layer maps
    the full frozen caffe.proto enum table."""
    from analytics_zoo_tpu.models.caffe import normalize_v1_layer

    assert normalize_v1_layer({"type": 4})["type"] == "Convolution"
    assert normalize_v1_layer({"type": 14})["type"] == "InnerProduct"
    assert normalize_v1_layer({"type": "POOLING"})["type"] == "Pooling"
    assert normalize_v1_layer({"type": "TANH"})["type"] == "TanH"
    # modern entries untouched
    assert normalize_v1_layer({"type": "Convolution"})["type"] \
        == "Convolution"
    with pytest.raises(NotImplementedError):
        normalize_v1_layer({"type": 9999})


def test_caffe_v1_blobs_parse():
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    data = encode_v1_caffemodel({"ip": [w]})
    blobs = parse_caffemodel(data)
    np.testing.assert_array_equal(blobs["ip"][0], w)
