"""Oracle tests for the image-op library additions (reference
feature/image/*.scala inventory — ImageBytesToMat, ChannelOrder,
ChannelScaledNormalizer, Filler, FixedCrop, Mirror, RandomCropper,
RandomPreprocessing, RandomResize, MatToFloats, AspectScale)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image.transforms import (
    ImageAspectScale,
    ImageBytesToMat,
    ImageChannelOrder,
    ImageChannelScaledNormalizer,
    ImageFiller,
    ImageFixedCrop,
    ImageMatToFloats,
    ImageMirror,
    ImagePixelBytesToMat,
    ImageRandomCropper,
    ImageRandomPreprocessing,
    ImageRandomResize,
    ImageResize,
)


def _img(h=24, w=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, size=(h, w, 3)).astype(np.uint8)


def test_bytes_to_mat_jpeg_roundtrip():
    import cv2

    img = _img()
    ok, buf = cv2.imencode(".png", img[:, :, ::-1])  # lossless
    out = ImageBytesToMat()(buf.tobytes())
    np.testing.assert_array_equal(out, img)
    out_bgr = ImageBytesToMat(order="BGR")(buf.tobytes())
    np.testing.assert_array_equal(out_bgr, img[:, :, ::-1])


def test_bytes_to_mat_rejects_garbage():
    with pytest.raises(ValueError):
        ImageBytesToMat()(b"not an image")


def test_pixel_bytes_to_mat():
    img = _img(4, 5)
    out = ImagePixelBytesToMat(4, 5, 3)(img.tobytes())
    np.testing.assert_array_equal(out, img)


def test_channel_order_swaps():
    img = _img()
    np.testing.assert_array_equal(ImageChannelOrder()(img),
                                  img[:, :, ::-1])


def test_channel_scaled_normalizer_oracle():
    img = _img()
    out = ImageChannelScaledNormalizer(10, 20, 30, 0.5)(img)
    expect = (img.astype(np.float32) - [10, 20, 30]) * 0.5
    np.testing.assert_allclose(out, expect)


def test_filler_fills_region():
    img = _img(10, 10)
    out = ImageFiller(0.2, 0.2, 0.5, 0.5, value=7)(img)
    assert (out[2:5, 2:5] == 7).all()
    np.testing.assert_array_equal(out[6:], img[6:])  # rest untouched


def test_fixed_crop_normalized_and_pixel():
    img = _img(20, 40)
    out = ImageFixedCrop(0.25, 0.5, 0.75, 1.0, normalized=True)(img)
    np.testing.assert_array_equal(out, img[10:20, 10:30])
    out2 = ImageFixedCrop(5, 2, 15, 12, normalized=False)(img)
    np.testing.assert_array_equal(out2, img[2:12, 5:15])
    # clipping keeps coordinates inside the image
    out3 = ImageFixedCrop(-5, -5, 999, 999, normalized=False)(img)
    np.testing.assert_array_equal(out3, img)


def test_mirror_deterministic():
    img = _img()
    np.testing.assert_array_equal(ImageMirror()(img), img[:, ::-1])


def test_random_cropper_shapes_and_center():
    img = _img(30, 30)
    out = ImageRandomCropper(12, 10, mirror=False)(img)
    assert out.shape == (10, 12, 3)
    c = ImageRandomCropper(12, 10, mirror=False, cropper_method="center")(img)
    np.testing.assert_array_equal(c, img[10:20, 9:21])


def test_random_preprocessing_prob_bounds():
    img = _img()
    always = ImageRandomPreprocessing(ImageMirror(), prob=1.0)(img)
    np.testing.assert_array_equal(always, img[:, ::-1])
    never = ImageRandomPreprocessing(ImageMirror(), prob=0.0)(img)
    np.testing.assert_array_equal(never, img)


def test_random_resize_short_side_in_range():
    img = _img(20, 40)
    out = ImageRandomResize(10, 14)(img)
    short = min(out.shape[:2])
    assert 10 <= short <= 14
    # aspect preserved within rounding
    assert abs(out.shape[1] / out.shape[0] - 2.0) < 0.2


def test_mat_to_floats():
    img = _img()
    out = ImageMatToFloats()(img)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, img.astype(np.float32))


def test_aspect_scale_respects_max():
    img = _img(100, 400)
    out = ImageAspectScale(min_size=60, max_size=120)(img)
    assert max(out.shape[:2]) <= 120
    assert abs(out.shape[1] / out.shape[0] - 4.0) < 0.2


def test_resize_matches_cv2_oracle():
    import cv2

    img = _img(17, 23)
    ours = ImageResize(9, 13)(img)
    oracle = cv2.resize(img, (13, 9), interpolation=cv2.INTER_LINEAR)
    # with cv2 present the op IS cv2 (reference backend): exact match
    np.testing.assert_array_equal(ours, oracle)


def test_random_cropper_rejects_small_input():
    img = _img(8, 8)
    with pytest.raises(ValueError, match="smaller than crop"):
        ImageRandomCropper(16, 16)(img)


class TestNativeBatchAssembly:
    """C++ threaded batch assembly vs the numpy path (bit-identical), on
    variable-size images with crops + flips."""

    def _images(self, n=12, seed=0):
        rng = np.random.default_rng(seed)
        return [rng.integers(0, 256, size=(
            int(rng.integers(40, 64)), int(rng.integers(40, 64)), 3),
            dtype=np.uint8) for _ in range(n)]

    def test_native_matches_numpy(self):
        from analytics_zoo_tpu import native
        from analytics_zoo_tpu.feature.image.transforms import (
            assemble_crop_batch,
        )

        imgs = self._images()
        rng = np.random.default_rng(7)
        offsets = np.stack([
            [rng.integers(0, im.shape[0] - 32 + 1),
             rng.integers(0, im.shape[1] - 32 + 1)] for im in imgs
        ]).astype(np.int32)
        flips = rng.random(len(imgs)) < 0.5
        assert flips.any() and (~flips).any()

        lib = native.build_native()
        if lib is None:
            import pytest

            pytest.skip("no C++ compiler available")
        got = assemble_crop_batch(imgs, 32, 32, offsets=offsets,
                                  flips=flips)
        # numpy oracle path (force fallback)
        saved, native.lib = native.lib, None
        try:
            want = assemble_crop_batch(imgs, 32, 32, offsets=offsets,
                                       flips=flips)
        finally:
            native.lib = saved
        assert got.shape == (12, 32, 32, 3) and got.dtype == np.uint8
        np.testing.assert_array_equal(got, want)

    def test_seeded_rng_reproducible(self):
        from analytics_zoo_tpu.feature.image.transforms import (
            assemble_crop_batch,
        )

        imgs = self._images(seed=1)
        a = assemble_crop_batch(imgs, 24, 24,
                                rng=np.random.default_rng(3))
        b = assemble_crop_batch(imgs, 24, 24,
                                rng=np.random.default_rng(3))
        np.testing.assert_array_equal(a, b)


def test_assemble_crop_batch_validation():
    from analytics_zoo_tpu.feature.image.transforms import (
        assemble_crop_batch,
    )

    imgs = [np.zeros((30, 30, 3), np.uint8)]
    # randomness without an rng is an error (no hidden fixed seed)
    with pytest.raises(ValueError, match="rng"):
        assemble_crop_batch(imgs, 24, 24)
    # out-of-bounds explicit offsets fail loudly on BOTH paths
    with pytest.raises(ValueError, match="out of bounds"):
        assemble_crop_batch(imgs, 24, 24, offsets=[[10, 0]],
                            flips=[False])
    # explicit flips without offsets are honored (not overwritten)
    out1 = assemble_crop_batch(imgs, 24, 24,
                               rng=np.random.default_rng(0),
                               flips=np.asarray([True]))
    assert out1.shape == (1, 24, 24, 3)


def test_native_resize_matches_cv2():
    """zoo_resize_bilinear_u8 vs cv2 INTER_LINEAR (the Python fallback):
    same half-pixel-center convention, so results agree to +-1 uint8
    rounding at every pixel."""
    import cv2

    from analytics_zoo_tpu import native
    from analytics_zoo_tpu.feature.image.transforms import resize_batch

    lib = native.build_native()
    if lib is None:
        pytest.skip("no C++ compiler available")
    rng = np.random.default_rng(11)
    batch = rng.integers(0, 256, size=(6, 37, 53, 3), dtype=np.uint8)
    for oh, ow in [(24, 24), (64, 48), (37, 53)]:
        got = resize_batch(batch, oh, ow)
        assert got.shape == (6, oh, ow, 3) and got.dtype == np.uint8
        want = np.stack([
            cv2.resize(im, (ow, oh), interpolation=cv2.INTER_LINEAR)
            for im in batch
        ])
        diff = np.abs(got.astype(int) - want.astype(int))
        assert diff.max() <= 1, (oh, ow, diff.max())
        # identity resize is exact
    np.testing.assert_array_equal(resize_batch(batch, 37, 53), batch)


def test_resize_batch_fallback_matches_native():
    from analytics_zoo_tpu import native
    from analytics_zoo_tpu.feature.image.transforms import resize_batch

    lib = native.build_native()
    if lib is None:
        pytest.skip("no C++ compiler available")
    rng = np.random.default_rng(12)
    batch = rng.integers(0, 256, size=(3, 40, 40, 1), dtype=np.uint8)
    got = resize_batch(batch, 20, 30)
    saved, native.lib = native.lib, None
    try:
        want = resize_batch(batch, 20, 30)
    finally:
        native.lib = saved
    diff = np.abs(got.astype(int) - want.astype(int))
    assert diff.max() <= 1


def test_stale_native_lib_rebuilds(tmp_path, monkeypatch):
    """A .so built from older source (missing a new symbol) must not
    crash import or build_native — it rebuilds from current source."""
    import subprocess

    from analytics_zoo_tpu import native

    old_src = tmp_path / "old.cpp"
    old_src.write_text(
        'extern "C" { unsigned zoo_crc32c(const char*, unsigned long)'
        "{ return 0; } }")
    stale = tmp_path / "libzoonative.so"
    r = subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o",
                        str(stale), str(old_src)], capture_output=True)
    if r.returncode != 0:
        pytest.skip("no compiler")
    monkeypatch.setattr(native, "_SO", str(stale))
    # build_native sees an existing-but-stale .so: must rebuild, not raise
    lib = native.build_native()
    assert lib is not None and hasattr(lib._dll, "zoo_assemble_batch")
