"""Oracle tests for the image-op library additions (reference
feature/image/*.scala inventory — ImageBytesToMat, ChannelOrder,
ChannelScaledNormalizer, Filler, FixedCrop, Mirror, RandomCropper,
RandomPreprocessing, RandomResize, MatToFloats, AspectScale)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image.transforms import (
    ImageAspectScale,
    ImageBytesToMat,
    ImageChannelOrder,
    ImageChannelScaledNormalizer,
    ImageFiller,
    ImageFixedCrop,
    ImageMatToFloats,
    ImageMirror,
    ImagePixelBytesToMat,
    ImageRandomCropper,
    ImageRandomPreprocessing,
    ImageRandomResize,
    ImageResize,
)


def _img(h=24, w=32, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, size=(h, w, 3)).astype(np.uint8)


def test_bytes_to_mat_jpeg_roundtrip():
    import cv2

    img = _img()
    ok, buf = cv2.imencode(".png", img[:, :, ::-1])  # lossless
    out = ImageBytesToMat()(buf.tobytes())
    np.testing.assert_array_equal(out, img)
    out_bgr = ImageBytesToMat(order="BGR")(buf.tobytes())
    np.testing.assert_array_equal(out_bgr, img[:, :, ::-1])


def test_bytes_to_mat_rejects_garbage():
    with pytest.raises(ValueError):
        ImageBytesToMat()(b"not an image")


def test_pixel_bytes_to_mat():
    img = _img(4, 5)
    out = ImagePixelBytesToMat(4, 5, 3)(img.tobytes())
    np.testing.assert_array_equal(out, img)


def test_channel_order_swaps():
    img = _img()
    np.testing.assert_array_equal(ImageChannelOrder()(img),
                                  img[:, :, ::-1])


def test_channel_scaled_normalizer_oracle():
    img = _img()
    out = ImageChannelScaledNormalizer(10, 20, 30, 0.5)(img)
    expect = (img.astype(np.float32) - [10, 20, 30]) * 0.5
    np.testing.assert_allclose(out, expect)


def test_filler_fills_region():
    img = _img(10, 10)
    out = ImageFiller(0.2, 0.2, 0.5, 0.5, value=7)(img)
    assert (out[2:5, 2:5] == 7).all()
    np.testing.assert_array_equal(out[6:], img[6:])  # rest untouched


def test_fixed_crop_normalized_and_pixel():
    img = _img(20, 40)
    out = ImageFixedCrop(0.25, 0.5, 0.75, 1.0, normalized=True)(img)
    np.testing.assert_array_equal(out, img[10:20, 10:30])
    out2 = ImageFixedCrop(5, 2, 15, 12, normalized=False)(img)
    np.testing.assert_array_equal(out2, img[2:12, 5:15])
    # clipping keeps coordinates inside the image
    out3 = ImageFixedCrop(-5, -5, 999, 999, normalized=False)(img)
    np.testing.assert_array_equal(out3, img)


def test_mirror_deterministic():
    img = _img()
    np.testing.assert_array_equal(ImageMirror()(img), img[:, ::-1])


def test_random_cropper_shapes_and_center():
    img = _img(30, 30)
    out = ImageRandomCropper(12, 10, mirror=False)(img)
    assert out.shape == (10, 12, 3)
    c = ImageRandomCropper(12, 10, mirror=False, cropper_method="center")(img)
    np.testing.assert_array_equal(c, img[10:20, 9:21])


def test_random_preprocessing_prob_bounds():
    img = _img()
    always = ImageRandomPreprocessing(ImageMirror(), prob=1.0)(img)
    np.testing.assert_array_equal(always, img[:, ::-1])
    never = ImageRandomPreprocessing(ImageMirror(), prob=0.0)(img)
    np.testing.assert_array_equal(never, img)


def test_random_resize_short_side_in_range():
    img = _img(20, 40)
    out = ImageRandomResize(10, 14)(img)
    short = min(out.shape[:2])
    assert 10 <= short <= 14
    # aspect preserved within rounding
    assert abs(out.shape[1] / out.shape[0] - 2.0) < 0.2


def test_mat_to_floats():
    img = _img()
    out = ImageMatToFloats()(img)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, img.astype(np.float32))


def test_aspect_scale_respects_max():
    img = _img(100, 400)
    out = ImageAspectScale(min_size=60, max_size=120)(img)
    assert max(out.shape[:2]) <= 120
    assert abs(out.shape[1] / out.shape[0] - 4.0) < 0.2


def test_resize_matches_cv2_oracle():
    import cv2

    img = _img(17, 23)
    ours = ImageResize(9, 13)(img)
    oracle = cv2.resize(img, (13, 9), interpolation=cv2.INTER_LINEAR)
    # with cv2 present the op IS cv2 (reference backend): exact match
    np.testing.assert_array_equal(ours, oracle)


def test_random_cropper_rejects_small_input():
    img = _img(8, 8)
    with pytest.raises(ValueError, match="smaller than crop"):
        ImageRandomCropper(16, 16)(img)
