"""Layer oracle tests — torch (CPU) as the reference implementation, the
analogue of the reference's KerasBaseSpec oracle strategy (SURVEY.md §4:
spawn real Keras, compare outputs per layer; here torch is in-process)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def apply_layer(layer, x, params=None, rng=None, training=False):
    layer.ensure_built(tuple(np.shape(x))[1:])
    if params is None:
        # PRNG keys are arrays — `rng or default` truthiness would raise
        params = layer.init_params(
            rng if rng is not None else jax.random.PRNGKey(0)
        )
    state = layer.init_state()
    out, _ = layer.apply(params, jnp.asarray(x), state=state or None,
                         training=training, rng=rng)
    return np.asarray(out), params


class TestDenseOracle:
    def test_vs_torch_linear(self):
        import torch

        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        x = np.random.default_rng(0).normal(size=(4, 7)).astype(np.float32)
        layer = Dense(5, activation="tanh")
        out, params = apply_layer(layer, x)

        lin = torch.nn.Linear(7, 5)
        with torch.no_grad():
            lin.weight.copy_(torch.from_numpy(
                np.asarray(params["kernel"]).T))
            lin.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
            ref = torch.tanh(lin(torch.from_numpy(x))).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestConvOracle:
    def test_conv2d_vs_torch(self):
        import torch

        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
        )

        x = np.random.default_rng(0).normal(
            size=(2, 9, 9, 3)).astype(np.float32)
        layer = Convolution2D(4, 3, 3, subsample=(2, 2))
        out, params = apply_layer(layer, x)

        conv = torch.nn.Conv2d(3, 4, 3, stride=2)
        with torch.no_grad():
            # HWIO -> OIHW
            w = np.transpose(np.asarray(params["kernel"]), (3, 2, 0, 1))
            conv.weight.copy_(torch.from_numpy(w))
            conv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
            ref = conv(torch.from_numpy(
                np.transpose(x, (0, 3, 1, 2)))).numpy()
        ref = np.transpose(ref, (0, 2, 3, 1))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
        assert out.shape[1:] == layer.compute_output_shape(
            (None, 9, 9, 3))[1:]
        assert out.shape[0] == 2

    def test_maxpool_vs_torch(self):
        import torch

        from analytics_zoo_tpu.pipeline.api.keras.layers import MaxPooling2D

        x = np.random.default_rng(1).normal(
            size=(2, 8, 8, 3)).astype(np.float32)
        layer = MaxPooling2D(pool_size=(2, 2))
        out, _ = apply_layer(layer, x)
        ref = torch.nn.functional.max_pool2d(
            torch.from_numpy(np.transpose(x, (0, 3, 1, 2))), 2
        ).numpy()
        np.testing.assert_allclose(
            out, np.transpose(ref, (0, 2, 3, 1)), rtol=1e-6)


class TestRecurrentOracle:
    def test_lstm_vs_torch(self):
        import torch

        from analytics_zoo_tpu.pipeline.api.keras.layers import LSTM

        b, t, f, u = 3, 6, 5, 4
        x = np.random.default_rng(2).normal(size=(b, t, f)).astype(
            np.float32)
        layer = LSTM(u, activation="tanh", inner_activation="sigmoid",
                     return_sequences=True)
        out, params = apply_layer(layer, x)

        ref_lstm = torch.nn.LSTM(f, u, batch_first=True)
        with torch.no_grad():
            # ours: i,f,g,o fused (in, 4u); torch: (4u, in) order i,f,g,o
            ref_lstm.weight_ih_l0.copy_(torch.from_numpy(
                np.asarray(params["kernel"]).T))
            ref_lstm.weight_hh_l0.copy_(torch.from_numpy(
                np.asarray(params["recurrent_kernel"]).T))
            ref_lstm.bias_ih_l0.copy_(torch.from_numpy(
                np.asarray(params["bias"])))
            ref_lstm.bias_hh_l0.zero_()
            ref, _ = ref_lstm(torch.from_numpy(x))
        np.testing.assert_allclose(out, ref.numpy(), rtol=1e-4, atol=1e-5)

    def test_gru_shapes_and_last_step(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import GRU

        x = np.random.default_rng(3).normal(size=(2, 5, 3)).astype(
            np.float32)
        seq_layer = GRU(4, return_sequences=True)
        seq, params = apply_layer(seq_layer, x)
        last_layer = GRU(4, return_sequences=False)
        last_layer.ensure_built((5, 3))
        last, _ = last_layer.apply(params, jnp.asarray(x))
        np.testing.assert_allclose(seq[:, -1], np.asarray(last), rtol=1e-5)

    def test_bidirectional_concat(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            LSTM,
            Bidirectional,
        )

        x = np.random.default_rng(4).normal(size=(2, 5, 3)).astype(
            np.float32)
        layer = Bidirectional(LSTM(4, return_sequences=True))
        out, _ = apply_layer(layer, x)
        assert out.shape == (2, 5, 8)

    def test_time_distributed_dense(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            TimeDistributed,
        )

        x = np.random.default_rng(5).normal(size=(2, 5, 3)).astype(
            np.float32)
        layer = TimeDistributed(Dense(7))
        out, params = apply_layer(layer, x)
        assert out.shape == (2, 5, 7)
        # same as applying dense per step
        ref = x @ np.asarray(params["inner"]["kernel"]) + np.asarray(
            params["inner"]["bias"])
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestBatchNorm:
    def test_train_eval_and_stats(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            BatchNormalization,
        )

        x = np.random.default_rng(6).normal(
            loc=3.0, scale=2.0, size=(16, 4)).astype(np.float32)
        layer = BatchNormalization(momentum=0.0)  # new stats = batch stats
        layer.ensure_built((4,))
        params = layer.init_params(jax.random.PRNGKey(0))
        state = layer.init_state()
        out, new_state = layer.call(params, jnp.asarray(x), state=state,
                                    training=True)
        np.testing.assert_allclose(np.asarray(out).mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out).std(0), 1.0, atol=1e-2)
        np.testing.assert_allclose(np.asarray(new_state["moving_mean"]),
                                   x.mean(0), rtol=1e-5)
        # eval mode uses moving stats
        out_eval, _ = layer.call(params, jnp.asarray(x), state=new_state,
                                 training=False)
        np.testing.assert_allclose(np.asarray(out_eval).mean(0), 0.0,
                                   atol=1e-4)


class TestEmbeddingAndAdvanced:
    def test_embedding_lookup(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import Embedding

        w = np.random.default_rng(7).normal(size=(10, 4)).astype(np.float32)
        layer = Embedding(10, 4, weights=w)
        ids = np.array([[1, 2], [9, 0]], dtype=np.int32)
        out, _ = apply_layer(layer, ids)
        np.testing.assert_allclose(out, w[ids], rtol=1e-6)

    def test_prelu_leakyrelu(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            LeakyReLU,
            PReLU,
        )

        x = np.array([[-2.0, 3.0]], dtype=np.float32)
        out, _ = apply_layer(LeakyReLU(alpha=0.1), x)
        np.testing.assert_allclose(out, [[-0.2, 3.0]], rtol=1e-6)
        out, params = apply_layer(PReLU(), x)
        np.testing.assert_allclose(out, [[-0.5, 3.0]], rtol=1e-6)


class TestTransformer:
    def test_transformer_forward_and_causality(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=50, seq_len=8, n_block=2, n_head=2,
                                 hidden_size=16, hidden_drop=0.0,
                                 attn_drop=0.0, embedding_drop=0.0)
        tokens = np.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=np.int32) - 1
        pos = np.arange(8, dtype=np.int32)[None]
        params = layer.init_params(jax.random.PRNGKey(0))
        out = layer.call(params, [jnp.asarray(tokens), jnp.asarray(pos)])
        assert out.shape == (1, 8, 16)
        # causality: changing a later token must not affect earlier outputs
        tokens2 = tokens.copy()
        tokens2[0, -1] = 40
        out2 = layer.call(params, [jnp.asarray(tokens2), jnp.asarray(pos)])
        np.testing.assert_allclose(np.asarray(out)[:, :-1],
                                   np.asarray(out2)[:, :-1], atol=1e-5)
        assert not np.allclose(np.asarray(out)[:, -1],
                               np.asarray(out2)[:, -1])

    def test_bert_outputs_and_mask(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import BERT

        layer = BERT(vocab=30, hidden_size=16, n_block=2, n_head=2,
                     seq_len=10, intermediate_size=32, hidden_p_drop=0.0,
                     attn_p_drop=0.0)
        b, l = 2, 10
        tokens = np.random.default_rng(8).integers(0, 30, (b, l))
        types = np.zeros((b, l), np.int32)
        pos = np.tile(np.arange(l), (b, 1))
        mask = np.ones((b, l), np.float32)
        mask[:, 6:] = 0.0
        params = layer.init_params(jax.random.PRNGKey(0))
        seq, pooled = layer.call(
            params, [jnp.asarray(tokens), jnp.asarray(types),
                     jnp.asarray(pos), jnp.asarray(mask)])
        assert seq.shape == (b, l, 16) and pooled.shape == (b, 16)
        # masked positions must not influence visible outputs
        tokens2 = tokens.copy()
        tokens2[:, 7] = (tokens2[:, 7] + 5) % 30
        seq2, _ = layer.call(
            params, [jnp.asarray(tokens2), jnp.asarray(types),
                     jnp.asarray(pos), jnp.asarray(mask)])
        np.testing.assert_allclose(np.asarray(seq)[:, :6],
                                   np.asarray(seq2)[:, :6], atol=1e-5)


class TestAutograd:
    def test_custom_loss_trains(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api import autograd as A
        from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        def mean_absolute_error(y_true, y_pred):
            return A.mean(A.abs(y_true - y_pred), axis=1)

        rng = np.random.default_rng(9)
        x = rng.normal(size=(256, 6)).astype(np.float32)
        w = rng.normal(size=(6, 2)).astype(np.float32)
        y = x @ w
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        model = Sequential()
        model.add(Dense(2, input_shape=(6,)))
        model.compile(optimizer=Adam(lr=0.05),
                      loss=CustomLoss(mean_absolute_error, [2]))
        model.fit(x, y, batch_size=64, nb_epoch=30)
        hist = model._estimator.history
        assert hist[-1]["loss"] < 0.25 * hist[0]["loss"]

    def test_lambda_layer_in_graph(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.autograd import Lambda
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model

        inp = Input(shape=(4,))
        doubled = Lambda(lambda v: v * 2.0)(inp)
        model = Model(inp, doubled)
        params, state = model.build_params()
        x = np.ones((2, 4), np.float32)
        out, _ = model.forward(params, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), 2 * x)

    def test_variable_math_graph(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api import autograd as A
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model

        ia, ib = Input(shape=(3,)), Input(shape=(3,))
        out = A.sum((ia - ib) ** 2.0, axis=1, keepdims=True)
        model = Model([ia, ib], out)
        params, _ = model.build_params()
        a = np.array([[1.0, 2.0, 3.0]], np.float32)
        b = np.array([[1.0, 0.0, 0.0]], np.float32)
        res, _ = model.forward(params, [jnp.asarray(a), jnp.asarray(b)])
        np.testing.assert_allclose(np.asarray(res), [[13.0]], rtol=1e-6)


def test_from_logits_losses_registered():
    """Registry names for the from-logits variants (used by the
    transformer bench and tfpark) match their probability twins."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss

    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 6)),
                         jnp.float32)
    y = jnp.asarray([0, 2, 5, 1], jnp.int32)
    a = get_loss("sparse_categorical_crossentropy_from_logits")
    b = get_loss("sparse_categorical_crossentropy")
    np.testing.assert_allclose(
        np.asarray(a.fn(y, logits)),
        np.asarray(b.fn(y, jax.nn.softmax(logits, axis=-1))),
        rtol=1e-5, atol=1e-6)
    yb = jnp.asarray([0.0, 1.0, 1.0, 0.0])
    lb = jnp.asarray([-2.0, 3.0, 0.5, -0.5])
    c = get_loss("binary_crossentropy_from_logits")
    d = get_loss("binary_crossentropy")
    np.testing.assert_allclose(
        np.asarray(c.fn(yb, lb)),
        np.asarray(d.fn(yb, jax.nn.sigmoid(lb))), rtol=1e-5, atol=1e-6)


def test_transformer_remat_matches_baseline(zoo_ctx):
    """remat=True (jax.checkpoint per block) must be a pure memory/FLOP
    trade: identical outputs AND gradients to the non-remat stack."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.keras.layers import TransformerLayer

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 50, size=(2, 12)), jnp.int32)

    base = TransformerLayer(vocab=50, seq_len=12, n_block=2, n_head=2,
                            hidden_size=16, embedding_drop=0.0,
                            hidden_drop=0.0, attn_drop=0.0)
    params = base.init_params(jax.random.PRNGKey(0))
    def loss(layer, p):
        return jnp.sum(layer.call(p, toks, training=True,
                                  rng=jax.random.PRNGKey(1)) ** 2)

    la, ga = jax.value_and_grad(lambda p: loss(base, p))(params)
    # every checkpoint policy must be a pure memory/FLOP trade
    for policy in (True, "dots", "attn"):
        rem = TransformerLayer(vocab=50, seq_len=12, n_block=2, n_head=2,
                               hidden_size=16, embedding_drop=0.0,
                               hidden_drop=0.0, attn_drop=0.0,
                               remat=policy)
        lb, gb = jax.value_and_grad(lambda p: loss(rem, p))(params)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6,
                                   err_msg=str(policy))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5), ga, gb)
    import pytest

    with pytest.raises(ValueError, match="remat"):
        TransformerLayer(vocab=50, seq_len=12, n_block=1, n_head=2,
                         hidden_size=16, remat="bogus")


def test_from_logits_losses_are_f32_under_bf16():
    """VERDICT r03 item 2: the from-logits CE must compute in f32 even
    when the model computes in bf16 — a bf16 log-softmax over a wide
    vocab axis corrupts the normalizer tail."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.api.keras.objectives import (
        binary_crossentropy_from_logits,
        sparse_categorical_crossentropy_from_logits,
    )

    rng = np.random.default_rng(0)
    logits32 = jnp.asarray(rng.normal(size=(4, 32768)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 32768, size=(4,)))
    want = sparse_categorical_crossentropy_from_logits(labels, logits32)
    got = sparse_categorical_crossentropy_from_logits(
        labels, logits32.astype(jnp.bfloat16))
    # bf16 INPUT quantization costs a little; the f32 softmax keeps the
    # error at input-precision scale instead of normalizer-accumulation
    # scale
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2)
    assert got.dtype == jnp.float32

    blog = jnp.asarray(rng.normal(size=(8, 1)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 2, size=(8, 1)).astype(np.float32))
    got_b = binary_crossentropy_from_logits(y, blog.astype(jnp.bfloat16))
    assert got_b.dtype == jnp.float32
