"""zooelastic: the elastic training runtime (ISSUE 16) — lease-based
membership (elastic/membership.py), deterministic chaos
(elastic/chaos.py), the training supervisor (elastic/supervisor.py),
and THE acceptance run: a 4-worker cohort losing one worker to
``kill -9`` and another to SIGTERM mid-``fit()`` finishes unattended
with a trajectory bit-exact against the uninterrupted run."""

import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.elastic import (
    ChaosEvent, ChaosSchedule, ElasticSession, GenerationChange,
    MembershipLedger, TrainSupervisor, equal_shares, rebalance_shares,
)
from analytics_zoo_tpu.elastic import supervisor as supervisor_mod
from analytics_zoo_tpu.elastic.membership import fget
from analytics_zoo_tpu.metrics import StragglerBoard
from analytics_zoo_tpu.serving import FileBroker, InMemoryBroker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(params=["memory", "file", "redis"])
def broker(request, tmp_path):
    if request.param == "memory":
        return InMemoryBroker()
    if request.param == "file":
        return FileBroker(str(tmp_path / "spool"))
    spec = os.environ.get("ZOO_TEST_REDIS")
    if not spec:
        pytest.skip("set ZOO_TEST_REDIS=host:port to run redis "
                    "membership tests")
    from analytics_zoo_tpu.serving.broker import connect_broker

    return connect_broker(spec)


# ---------------------------------------------------------------------------
# Membership ledger (lease-based liveness + the generation counter)
# ---------------------------------------------------------------------------


def test_join_scan_generation_lifecycle(broker):
    led = MembershipLedger(broker, prefix="t-elastic", lease_ms=400)
    assert led.members() == []
    h0 = led.join("w0")
    doc, changed = led.scan()
    assert changed and doc["generation"] == 1 and doc["members"] == ["w0"]
    # stable membership: scan does NOT bump
    doc2, changed = led.scan()
    assert not changed and doc2["generation"] == 1

    h1 = led.join("w1")
    doc, changed = led.scan()
    assert changed and doc["generation"] == 2
    assert doc["members"] == ["w0", "w1"] and doc["world"] == 2

    # graceful leave drops the member on the NEXT scan (no lease wait)
    h1.leave()
    doc, changed = led.scan()
    assert changed and doc["generation"] == 3 and doc["members"] == ["w0"]

    # kill -9 shape: keepalive stops, nothing released -> the member
    # survives exactly until the lease expires
    h0._stop.set()
    assert led.members() == ["w0"]
    time.sleep(0.6)
    doc, changed = led.scan()
    assert changed and doc["generation"] == 4 and doc["world"] == 0


def test_keepalive_outlives_many_lease_periods(broker):
    led = MembershipLedger(broker, prefix="t-keep", lease_ms=150)
    h = led.join("w0")
    time.sleep(1.0)  # ~7 lease periods
    assert led.members() == ["w0"]
    h.leave()


def test_respawn_waits_out_dead_incarnations_lease(broker):
    led = MembershipLedger(broker, prefix="t-slot", lease_ms=400)
    h = led.join("w0")
    h._stop.set()  # dead incarnation: lease still ticking
    t0 = time.monotonic()
    h2 = led.join("w0")  # blocks until the broker expires the claim
    waited = time.monotonic() - t0
    assert waited < 2.0  # well under the join timeout
    assert led.members() == ["w0"]
    h2.leave()


def test_join_timeout_when_slot_never_frees(broker):
    led = MembershipLedger(broker, prefix="t-timeout", lease_ms=300)
    h = led.join("w0")  # keepalive KEEPS extending
    led2 = MembershipLedger(broker, prefix="t-timeout", lease_ms=300)
    with pytest.raises(TimeoutError):
        led2.join("w0", timeout_ms=700)
    h.leave()


def test_concurrent_joins_all_land(broker):
    """Regression pin: per-worker roster hashes.  A SHARED roster hash
    is a read-modify-write race on FileBroker (hset reads the file and
    rewrites it), so simultaneous joins silently dropped each other and
    the supervisor formed a cohort of 1 out of 4."""
    import concurrent.futures as cf

    led = MembershipLedger(broker, prefix="t-race", lease_ms=2000)
    with cf.ThreadPoolExecutor(4) as ex:
        handles = list(ex.map(
            lambda i: led.join(f"w{i}"), range(4)))
    assert led.members() == ["w0", "w1", "w2", "w3"]
    doc, _ = led.scan()
    assert doc["world"] == 4
    for h in handles:
        h.leave()


def test_generation_change_carries_doc():
    doc = {"generation": 5, "world": 3, "members": ["w0", "w1", "w2"]}
    e = GenerationChange(doc)
    assert e.doc == doc and "5" in str(e) and "world 3" in str(e)


def test_fget_tolerates_bytes():
    assert fget({b"k": b"v"}, "k") == "v"
    assert fget({"k": "v"}, "k") == "v"
    assert fget({}, "k", "d") == "d"
    assert fget(None, "k", "d") == "d"


# ---------------------------------------------------------------------------
# ElasticSession: the step barrier's read side
# ---------------------------------------------------------------------------


def test_session_sees_generation_bump_and_heartbeats():
    b = InMemoryBroker()
    led = MembershipLedger(b, prefix="t-sess", lease_ms=2000)
    h = led.join("w0")
    led.scan()  # -> generation 1
    s = ElasticSession(b, prefix="t-sess", generation=1, worker_id="w0",
                       start_step=10, min_poll_s=0.0)
    assert s.poll() is None  # generation unchanged
    assert s.step() == 11  # one dispatch counted on top of start_step
    hb = b.hgetall(led.hb_key("w0"))
    assert fget(hb, "step") == "11" and fget(hb, "role") == "chief"

    led.join("w1")
    doc, changed = led.scan()  # -> generation 2
    assert changed
    got = s.poll()
    assert got is not None and got["generation"] == 2
    h.leave()


def test_session_consumes_stall_exactly_once():
    b = InMemoryBroker()
    led = MembershipLedger(b, prefix="t-stall", lease_ms=2000)
    s = ElasticSession(b, prefix="t-stall", worker_id="w0",
                       min_poll_s=0.0)
    b.hset(led.ctl_key("w0"), {"stall_s": "0.2"})
    t0 = time.monotonic()
    s.poll()
    assert time.monotonic() - t0 >= 0.2  # slept the injected stall
    assert b.hgetall(led.ctl_key("w0")) == {}  # consumed
    hb = b.hgetall(led.hb_key("w0"))
    assert float(fget(hb, "step_s")) >= 0.2  # visible to the board
    t0 = time.monotonic()
    s.poll()
    assert time.monotonic() - t0 < 0.15  # one-shot, not sticky


def test_session_rate_limits_broker_reads():
    b = InMemoryBroker()
    s = ElasticSession(b, prefix="t-rate", worker_id="w0",
                       min_poll_s=60.0)
    s.poll()  # first tick publishes
    led = MembershipLedger(b, prefix="t-rate")
    b.delete(led.hb_key("w0"))
    for _ in range(50):
        assert s.poll() is None
    assert b.hgetall(led.hb_key("w0")) == {}  # no broker traffic since
    assert s.step() == 51


# ---------------------------------------------------------------------------
# Chaos schedules
# ---------------------------------------------------------------------------


def test_chaos_parse_and_due():
    sch = ChaosSchedule.parse("kill@12:w1, term@20:w2, stall@16:w3:1.5")
    assert [(e.action, e.at_step, e.target) for e in sch.events] == [
        ("kill", 12, "w1"), ("stall", 16, "w3"), ("term", 20, "w2")]
    assert sch.events[1].arg == 1.5
    assert [e.target for e in sch.due(16)] == ["w1", "w3"]
    for e in sch.due(16):
        e.fired = True
    assert sch.due(16) == [] and not sch.done()
    sch.due(99)[0].fired = True
    assert sch.done()


def test_chaos_from_seed_deterministic_and_bounded():
    a = ChaosSchedule.from_seed(7, ["w0", "w1", "w2", "w3"], 100,
                                n_events=3)
    b = ChaosSchedule.from_seed(7, ["w0", "w1", "w2", "w3"], 100,
                                n_events=3)
    assert a.to_doc() == b.to_doc()  # reproducible from the seed
    targets = [e.target for e in a.events]
    assert len(set(targets)) == len(targets)  # distinct targets
    for e in a.events:
        assert 25 <= e.at_step <= 75  # middle half of the run


def test_chaos_rejects_unknown_action():
    with pytest.raises(ValueError):
        ChaosEvent(at_step=1, action="nuke", target="w0")
    with pytest.raises(ValueError):
        ChaosSchedule.parse("kill@12")


# ---------------------------------------------------------------------------
# Share arithmetic + straggler board (the rebalance signal path)
# ---------------------------------------------------------------------------


def test_equal_shares_largest_remainder():
    assert equal_shares(32, ["w0", "w1", "w2", "w3"]) == {
        "w0": 8, "w1": 8, "w2": 8, "w3": 8}
    s = equal_shares(32, ["w0", "w1", "w2"])
    assert sum(s.values()) == 32 and sorted(s.values()) == [10, 11, 11]
    assert equal_shares(5, []) == {}


def test_rebalance_preserves_global_batch_exactly():
    shares = equal_shares(32, ["w0", "w1", "w2", "w3"])
    new = rebalance_shares(shares, {"w2": 3.0})
    assert sum(new.values()) == 32  # THE invariant: global batch
    assert new["w2"] < shares["w2"]  # slow worker shrank
    assert all(new[w] >= shares[w] for w in ("w0", "w1", "w3"))
    assert min(new.values()) >= 1


def test_rebalance_min_share_floor_and_degenerate_inputs():
    new = rebalance_shares({"w0": 2, "w1": 2}, {"w1": 100.0})
    assert new["w1"] >= 1 and sum(new.values()) == 4
    assert rebalance_shares({}, {}) == {}
    # total too small to give everyone min_share: unchanged
    tiny = {"w0": 1, "w1": 1}
    assert rebalance_shares(tiny, {"w1": 9.0}, min_share=2) == tiny


def test_straggler_board_factors():
    b = StragglerBoard(window=16, min_steps=3)
    for _ in range(6):
        for w in ("w0", "w1", "w2"):
            b.observe(w, 0.1)
        b.observe("w3", 0.3)
    f = b.factors()
    assert abs(f["w0"] - 1.0) < 1e-6
    assert abs(f["w3"] - 3.0) < 1e-6
    assert b.slowdown("w3") == pytest.approx(3.0)
    b.forget("w3")
    assert "w3" not in b.factors()


def test_straggler_board_warmup_suppression():
    b = StragglerBoard(window=16, min_steps=5)
    assert b.observe("w0", 5.0) == 1.0  # thin history: no verdict
    assert b.factors() == {}


# ---------------------------------------------------------------------------
# Supervisor units (no subprocesses)
# ---------------------------------------------------------------------------


def test_supervisor_rejects_live_broker_and_missing_ckpt_dir(tmp_path):
    with pytest.raises(ValueError, match="broker spec"):
        TrainSupervisor(InMemoryBroker(), {"ckpt_dir": str(tmp_path)})
    with pytest.raises(ValueError, match="ckpt_dir"):
        TrainSupervisor("dir:" + str(tmp_path), {})


def test_supervisor_from_config_and_env_tier(tmp_path, monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv("ZOO_ELASTIC", "yes")
    monkeypatch.setenv("ZOO_ELASTIC_LEASE_MS", "1200")
    monkeypatch.setenv("ZOO_ELASTIC_MIN_WORKERS", "2")
    monkeypatch.setenv("ZOO_ELASTIC_GRACE_MS", "700")
    cfg = ZooConfig()
    assert (cfg.elastic, cfg.elastic_lease_ms, cfg.elastic_min_workers,
            cfg.elastic_grace_ms) == (True, 1200, 2, 700)
    sup = TrainSupervisor.from_config(
        cfg, "dir:" + str(tmp_path / "sp"),
        {"ckpt_dir": str(tmp_path / "ck")})
    assert (sup.lease_ms, sup.min_workers, sup.grace_ms) == (
        1200, 2, 700)


def test_zoo_config_rejects_bad_elastic_knobs(monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv("ZOO_ELASTIC", "sideways")
    with pytest.raises(ValueError, match="ZOO_ELASTIC"):
        ZooConfig()
    monkeypatch.delenv("ZOO_ELASTIC")
    monkeypatch.setenv("ZOO_ELASTIC_LEASE_MS", "50")  # below minimum
    with pytest.raises(ValueError, match="ZOO_ELASTIC_LEASE_MS"):
        ZooConfig()
    monkeypatch.setenv("ZOO_ELASTIC_LEASE_MS", "3000")
    monkeypatch.setenv("ZOO_ELASTIC_MIN_WORKERS", "0")
    with pytest.raises(ValueError, match="ZOO_ELASTIC_MIN_WORKERS"):
        ZooConfig()


def test_varz_and_render_elastic(tmp_path):
    sup = TrainSupervisor("dir:" + str(tmp_path / "sp"),
                          {"ckpt_dir": str(tmp_path / "ck")}, workers=4)
    sup._record_decision("rejoin", "leave", generation=3, world=3,
                         worker="w1")
    doc = supervisor_mod.varz_doc()
    assert any(s["current"]["target_workers"] == 4
               for s in doc["supervisors"])
    assert any(d["action"] == "rejoin" for d in doc["decisions"])

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from metrics_dump import render_elastic
    finally:
        sys.path.pop(0)
    out = []
    render_elastic({"elastic": doc}, out=out)
    text = "\n".join(out)
    assert "elastic: generation=" in text and "rejoin" in text
    out2 = []
    render_elastic({"elastic": doc}, prefix="zoo_prefetch", out=out2)
    assert out2 == []  # --prefix filters the panel out


# ---------------------------------------------------------------------------
# SIGTERM flight-dump vs async checkpoint writer (the ISSUE 16 race pin)
# ---------------------------------------------------------------------------


_SIGTERM_RACE_SCRIPT = r"""
import os, pickle, signal, sys, time
import numpy as np
from analytics_zoo_tpu.metrics.flight import get_flight_recorder
from analytics_zoo_tpu.pipeline.estimator import estimator as est_mod

flight = get_flight_recorder().install()

real_dump = pickle.dump
def slow_dump(obj, f, *a, **k):
    time.sleep(1.0)  # wide-open race window: writer mid-serialization
    return real_dump(obj, f, *a, **k)
pickle.dump = slow_dump

ck = est_mod._Checkpointer(sys.argv[1])
ck.save("race", {"params": np.zeros(8, np.float32), "global_step": 1,
                 "epoch": 1})
os.kill(os.getpid(), signal.SIGTERM)  # dump while the write is in flight
time.sleep(30)  # never reached
"""


def test_sigterm_dump_flushes_async_checkpoint_writer(tmp_path):
    """A SIGTERM flight dump must contain the in-flight snapshot's final
    ``ckpt`` complete event — the pre-dump hook joins the writer thread
    (bounded by ZOO_ELASTIC_GRACE_MS) before the ring is snapshotted.
    Before the fix the dump ended at phase=start and the snapshot died
    half-written with the process."""
    flight_dir = str(tmp_path / "flight")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZOO_FLIGHT_DIR=flight_dir, ZOO_ELASTIC_GRACE_MS="10000")
    p = subprocess.run(
        [sys.executable, "-c", _SIGTERM_RACE_SCRIPT,
         str(tmp_path / "ck")],
        env=env, cwd=REPO, timeout=120, capture_output=True, text=True)
    assert p.returncode != 0  # died to the SIGTERM, not the sleep
    dumps = [f for f in os.listdir(flight_dir) if f.endswith(".json")]
    assert dumps, p.stderr
    with open(os.path.join(flight_dir, dumps[0])) as f:
        doc = json.load(f)
    phases = [e.get("phase") for e in doc["events"]
              if e.get("kind") == "ckpt"]
    assert "complete" in phases, phases  # flushed BEFORE the snapshot
    # and the durable artifact is whole: LATEST names a loadable pickle
    with open(os.path.join(str(tmp_path / "ck"), "LATEST")) as f:
        name = f.read().strip()
    with open(os.path.join(str(tmp_path / "ck"), name), "rb") as f:
        payload = pickle.load(f)
    assert payload["global_step"] == 1


# ---------------------------------------------------------------------------
# THE acceptance run (ISSUE 16): 4 workers, kill -9 + SIGTERM mid-run,
# unattended completion, trajectory bit-exact vs the uninterrupted run
# ---------------------------------------------------------------------------


def _uninterrupted_params(spec, mesh):
    """The oracle trajectory: same model/data/plan, no faults, straight
    through in-process on a {data: mesh} mesh."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(seed=spec["seed"], mesh_shape={"data": mesh})
    m = Sequential()
    m.add(Dense(spec["hidden"], activation="relu",
                input_shape=(spec["in_dim"],)))
    m.add(Dense(spec["classes"], activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rng = np.random.default_rng(spec["seed"])
    x = rng.standard_normal(
        (spec["n"], spec["in_dim"])).astype(np.float32)
    y = rng.integers(0, spec["classes"],
                     size=(spec["n"],)).astype(np.int32)
    m.fit(x, y, batch_size=spec["batch_size"],
          nb_epoch=spec["nb_epoch"], plan=spec["plan"])
    return m, [h["loss"] for h in m._estimator.history]


def _latest_payload(ckpt_dir):
    with open(os.path.join(ckpt_dir, "LATEST")) as f:
        name = f.read().strip()
    with open(os.path.join(ckpt_dir, name), "rb") as f:
        return pickle.load(f)


def test_chaos_acceptance_kill9_and_sigterm_unattended(tmp_path):
    """4-worker TrainSupervisor over a dir: broker; chaos kills one
    worker with SIGKILL and another with SIGTERM mid-run.  The cohort
    must finish the full nb_epoch target with ZERO human intervention;
    every fault shows up in the decision log as
    chaos -> leave-rejoin -> respawn -> join-rejoin; the oracle re-picks
    EXACTLY once per generation change; and the final parameters are
    bit-exact against the uninterrupted single-leg run (resume from
    LATEST + resharding preserved the trajectory across every world
    size the run passed through)."""
    ck = str(tmp_path / "ckpt")
    spec = dict(ckpt_dir=ck, nb_epoch=6, plan="fsdp", k=1,
                throttle_s=0.08)
    sup = TrainSupervisor(
        "dir:" + str(tmp_path / "spool"), spec, workers=4,
        lease_ms=800, min_workers=1, interval=0.1,
        chaos=ChaosSchedule.parse("kill@12:w1,term@24:w2"),
        worker_env={"ZOO_FLIGHT_DIR": str(tmp_path / "flight")})
    res = sup.run(timeout_s=420)

    # unattended completion: full target reached, result posted
    assert res is not None and res["done"] == 1, sup.decision_log()
    steps_per_epoch = sup.spec["n"] // sup.spec["batch_size"]
    assert res["final_step"] == steps_per_epoch * sup.spec["nb_epoch"]

    log = sup.decision_log()
    by_action = {}
    for d in log:
        by_action.setdefault(d["action"], []).append(d)
    # both faults fired, at their scripted steps or the tick after
    chaos = {d["reason"]: d for d in by_action["chaos"]}
    assert set(chaos) == {"kill", "term"}
    for d in chaos.values():
        assert d["fired_step"] - d["at_step"] <= 3
    # each fault produced a leave-rejoin; each respawn a join-rejoin
    rejoins = by_action["rejoin"]
    assert sum(1 for d in rejoins if d["reason"] == "leave") >= 2
    assert sum(1 for d in rejoins if d["reason"] == "join") >= 3
    assert len(by_action["respawn"]) >= 2
    # every step is accounted for: any step past LATEST at a kill is
    # REPLAYED, not dropped — the decision log carries the replay count
    kills = [d for d in rejoins if d["reason"] == "leave"]
    assert all(d["steps_lost"] >= 0 for d in kills)

    # exactly ONE oracle re-pick per generation change that produced an
    # assignment, logged as a prediction (outcome fed on completion)
    repicks = sup.repick_log()
    assert len(repicks) == len(rejoins)
    assert [r["generation"] for r in repicks] == \
        [d["generation"] for d in rejoins]  # one per generation, in order
    assert all(r["pick"]["plan"] for r in repicks)
    done = by_action["done"][0]
    assert done["steps_per_sec"] > 0  # the outcome that closed the loop

    # trajectory: bit-exact against the uninterrupted run
    import jax

    m, full_losses = _uninterrupted_params(sup.spec, mesh=4)
    final = _latest_payload(ck)
    assert final["global_step"] == res["final_step"]
    chaos_final = [np.asarray(a) for a in
                   jax.tree_util.tree_leaves(final["params"])]
    clean_final = [np.asarray(a) for a in
                   jax.tree_util.tree_leaves(m.params)]
    assert len(chaos_final) == len(clean_final)
    for a, b in zip(chaos_final, clean_final):
        np.testing.assert_array_equal(a, b)  # BIT-exact
    # full per-epoch losses of the final leg line up with the clean run
    # (the leg's FIRST history entry may cover a partially-replayed
    # epoch — resumed mid-epoch its average spans fewer batches)
    for h in res["history"][1:]:
        np.testing.assert_allclose(
            h["loss"], full_losses[h["epoch"] - 1], rtol=1e-6)


def test_chaos_supervisor_over_redis_broker(tmp_path):
    """The chaos/supervision path over a REAL RedisBroker (the
    cross-host deployment shape): the membership ledger, assignment
    docs, chaos kill, respawn and the posted result all travel through
    redis instead of a shared filesystem.  The membership tests above
    already parametrize over redis via the ``broker`` fixture; this
    covers the full supervisor loop.  A unique prefix isolates the run
    on a shared server."""
    spec = os.environ.get("ZOO_TEST_REDIS")
    if not spec:
        pytest.skip("set ZOO_TEST_REDIS=host:port to run redis "
                    "supervisor/chaos tests")
    prefix = f"t-chaos-{os.getpid()}-{int(time.time())}"
    sup = TrainSupervisor(
        spec, dict(ckpt_dir=str(tmp_path / "ckpt"), nb_epoch=3,
                   plan="dp", k=1, throttle_s=0.08),
        workers=3, prefix=prefix, lease_ms=800, min_workers=1,
        interval=0.1, chaos=ChaosSchedule.parse("kill@10:w1"))
    res = sup.run(timeout_s=420)

    assert res is not None and res["done"] == 1, sup.decision_log()
    steps_per_epoch = sup.spec["n"] // sup.spec["batch_size"]
    assert res["final_step"] == steps_per_epoch * sup.spec["nb_epoch"]
    by_action = {}
    for d in sup.decision_log():
        by_action.setdefault(d["action"], []).append(d)
    assert by_action["chaos"][0]["reason"] == "kill"
    assert len(by_action["respawn"]) >= 1
    assert any(d["reason"] == "leave" for d in by_action["rejoin"])
    assert any(d["reason"] == "join" for d in by_action["rejoin"])
