"""Parallel host data plane (feature/prefetch.py): ordered deterministic
delivery, worker-exception propagation, clean shutdown, shard read-ahead,
estimator composition, and the --data-pipeline bench quick tier."""

import os
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.feature.common import FnPreprocessing
from analytics_zoo_tpu.feature.dataset import FeatureSet, ShardedFeatureSet
from analytics_zoo_tpu.feature.prefetch import (
    PrefetchFeatureSet,
    PrefetchPipeline,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def assert_streams_identical(a_batches, b_batches):
    assert len(a_batches) == len(b_batches)
    for a, b in zip(a_batches, b_batches):
        assert set(a) == set(b)
        for k in a:
            if isinstance(a[k], list):
                for ai, bi in zip(a[k], b[k]):
                    np.testing.assert_array_equal(ai, bi)
            else:
                np.testing.assert_array_equal(a[k], b[k])


@pytest.fixture()
def shard_dir(tmp_path):
    paths = []
    for i in range(5):
        p = tmp_path / f"shard{i}.npz"
        rng = np.random.default_rng(100 + i)
        np.savez(p, x=rng.standard_normal((13, 4)).astype(np.float32),
                 y=rng.integers(0, 3, size=(13,)).astype(np.int32))
        paths.append(str(p))
    return paths


def test_array_prefetch_byte_identical():
    x = np.arange(200 * 3, dtype=np.float32).reshape(200, 3)
    y = np.arange(200, dtype=np.int32)
    fs = FeatureSet.of(x, y)
    for kwargs in (
        dict(shuffle=True, seed=3, epoch=1),
        dict(shuffle=True, seed=3, epoch=1, start_batch=2),
        dict(shuffle=False, drop_last=False, pad_to_batch=8),
    ):
        serial = list(fs.batches(16, **kwargs))
        pre = list(fs.prefetch(depth=3, workers=2).batches(16, **kwargs))
        assert_streams_identical(serial, pre)


def test_transformed_prefetch_byte_identical_and_parallel():
    x = np.arange(120, dtype=np.float32).reshape(40, 3)
    seen_threads = set()

    def tf(record):
        seen_threads.add(threading.current_thread().name)
        return record * 2.0 + 1.0

    fs = FeatureSet.of(x).transform(FnPreprocessing(tf))
    serial = list(fs.batches(8, shuffle=True, seed=9, epoch=4))
    seen_threads.clear()
    pre = list(fs.prefetch(depth=4, workers=3).batches(
        8, shuffle=True, seed=9, epoch=4))
    assert_streams_identical(serial, pre)
    # the transform ran on pool workers, not the consumer thread
    assert all(t.startswith("zoo-prefetch") for t in seen_threads)


def test_nested_transforms_collapse_into_map_stage():
    x = np.arange(60, dtype=np.float32).reshape(20, 3)
    fs = FeatureSet.of(x).transform(
        FnPreprocessing(lambda r: r + 1.0)).transform(
        FnPreprocessing(lambda r: r * 3.0))
    serial = list(fs.batches(4, shuffle=True, seed=0, epoch=0))
    pre = list(fs.prefetch(depth=2, workers=2).batches(
        4, shuffle=True, seed=0, epoch=0))
    assert_streams_identical(serial, pre)


def test_sharded_prefetch_across_slice_boundary(shard_dir):
    # batch 8 over 13-record shards: every batch straddles shard
    # boundaries, and n_slices=5 keeps ONE shard resident, so the
    # resident slice advances (and read-ahead fires) mid-epoch
    fs = ShardedFeatureSet(shard_dir, n_slices=5)
    for kwargs in (dict(shuffle=True, seed=1, epoch=0),
                   dict(shuffle=True, seed=1, epoch=0, start_batch=3),
                   dict(shuffle=True, seed=2, epoch=5, drop_last=False,
                        pad_to_batch=4)):
        serial = list(fs.batches(8, **kwargs))
        pre = list(fs.prefetch(depth=3, workers=2).batches(8, **kwargs))
        assert_streams_identical(serial, pre)


def test_sharded_read_ahead_loads_next_shard_off_thread(shard_dir):
    load_threads = []

    def loader(path):
        load_threads.append(threading.current_thread().name)
        data = np.load(path)
        return {k: data[k] for k in data.files}

    fs = ShardedFeatureSet(shard_dir, n_slices=5, loader=loader,
                           sizer=lambda p: 13)
    pre = list(fs.prefetch(depth=3, workers=2).batches(
        8, shuffle=True, seed=1, epoch=0))
    assert pre  # consumed something
    # each shard loaded exactly once (read-ahead never duplicates work)
    assert len(load_threads) == len(shard_dir)
    # all but the first load were read-ahead submissions on the pool
    assert sum(t.startswith("zoo-prefetch") and "producer" not in t
               for t in load_threads) >= len(shard_dir) - 1
    # disabled again after iteration (no leaked pool reference)
    assert fs._ra_pool is None and fs._ra_futures == {}


def test_worker_exception_propagates_at_position_and_shuts_down():
    x = np.arange(64, dtype=np.float32).reshape(64, 1)

    def tf(record):
        if record[0] == 40.0:
            raise RuntimeError("boom at 40")
        return record

    fs = FeatureSet.of(x).transform(FnPreprocessing(tf))
    pre = fs.prefetch(depth=2, workers=2)
    it = pre.batches(8, shuffle=False)
    got = [next(it) for _ in range(5)]  # batches 0..4 are clean
    assert len(got) == 5
    with pytest.raises(RuntimeError, match="boom at 40"):
        next(it)  # batch 5 holds record 40
    # the pipeline shut down: no prefetch threads survive
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
            t.name.startswith("zoo-prefetch") and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name.startswith("zoo-prefetch") and t.is_alive()
                   for t in threading.enumerate())


def test_source_exception_propagates():
    def bad_source():
        yield {"x": np.zeros((2, 2))}
        raise ValueError("source died")

    pipe = PrefetchPipeline(bad_source(), workers=1, depth=2)
    it = iter(pipe)
    next(it)
    with pytest.raises(ValueError, match="source died"):
        next(it)


def test_clean_shutdown_mid_stream():
    x = np.zeros((1000, 4), np.float32)
    fs = FeatureSet.of(x).transform(FnPreprocessing(lambda r: r))
    gen = fs.prefetch(depth=4, workers=2).batches(4, shuffle=False)
    next(gen)
    next(gen)
    gen.close()  # GeneratorExit -> pipeline.close()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
            t.name == "zoo-prefetch-producer" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "zoo-prefetch-producer" and t.is_alive()
                   for t in threading.enumerate())


def test_prefetch_metrics_and_health():
    from analytics_zoo_tpu.metrics import (
        DataPipelineMetrics,
        MetricsRegistry,
        get_health,
        snapshot,
    )

    reg = MetricsRegistry(enabled=True)
    x = np.zeros((40, 2), np.float32)
    fs = FeatureSet.of(x)
    pre = PrefetchFeatureSet(fs, depth=2, workers=1,
                             metrics=DataPipelineMetrics(registry=reg))
    n = len(list(pre.batches(8, shuffle=False)))
    by_name = {s["name"]: s for s in snapshot(reg)["samples"]}
    assert by_name["zoo_data_prefetch_batches_total"]["value"] == n
    # one wait per delivered batch plus the end-of-stream get
    assert by_name["zoo_data_prefetch_consumer_wait_seconds"]["count"] \
        == n + 1
    assert by_name["zoo_data_prefetch_workers"]["value"] == 1
    assert by_name.get("zoo_data_prefetch_errors_total",
                       {"value": 0})["value"] == 0
    # the infeed-style heartbeat component unregistered itself on exit
    assert "data_prefetch" not in get_health().status()["components"]


def test_estimator_composes_prefetch_with_infeed(zoo_ctx):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    rng = np.random.default_rng(0)
    x = rng.standard_normal((128, 6)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)

    def fit(prefetch_workers):
        zoo_ctx.config.prefetch_workers = prefetch_workers
        zoo_ctx.config.prefetch_depth = 3
        model = Sequential()
        model.add(Dense(8, activation="relu", input_shape=(6,)))
        model.add(Dense(2, activation="softmax"))
        model.compile(optimizer="sgd",
                      loss="sparse_categorical_crossentropy")
        model.fit(x, y, batch_size=32, nb_epoch=2)
        return [h["loss"] for h in model._estimator.history]

    try:
        serial_losses = fit(0)
        prefetch_losses = fit(2)
    finally:
        zoo_ctx.config.prefetch_workers = 0
    # identical batch streams => identical training trajectories
    np.testing.assert_allclose(prefetch_losses, serial_losses, rtol=1e-6)


@pytest.mark.parametrize("bad", [{"depth": 0}, {"workers": 0}])
def test_pipeline_rejects_bad_knobs(bad):
    with pytest.raises(ValueError):
        PrefetchPipeline(iter([]), **bad)


def test_data_pipeline_bench_quick_tier(tmp_path):
    """CI guard: the quick-sized --data-pipeline bench must show the
    acceptance speedup (>= 2x with 4 workers on a sleep-bound loader)
    and a byte-identical stream, so pipeline regressions fail loudly."""
    import json

    import bench

    out = str(tmp_path / "BENCH_DATA_quick.json")
    doc = bench.data_pipeline_bench(
        n_shards=4, shard_records=32, batch_size=8,
        load_sleep_ms=15.0, transform_sleep_ms=1.0, out_path=out)
    assert doc["deterministic"], doc
    assert doc["speedup"] >= 2.0, doc
    with open(out) as f:
        artifact = json.load(f)
    assert artifact["prefetched_batches_per_sec"] > \
        artifact["serial_batches_per_sec"]
    assert "consumer_wait_s" in artifact
