"""Model-surface routed MoE (ops/moe.py + TransformerLayer moe_experts).

Pins the three contracts VERDICT r4 asked for:
- the routed FFN equals the dense mixture when nothing is dropped
  (dense-dispatch oracle, same role as ep_moe_mlp for moe_mlp_topk);
- under adversarially skewed routing, over-capacity tokens lose their
  expert contribution but are NOT silently zeroed at the block output
  (residual passthrough), the drop fraction is reported exactly, and the
  load-balancing aux loss flags the collapse;
- the aux loss reaches the estimator's training loss through the layer
  state channel and its gradient actually pushes the router toward
  balance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.moe import routed_ffn


def _moe_params(rng, d, e, m, gate_bias_to=None, gate_bias=10.0):
    ks = jax.random.split(rng, 4)
    gate = 0.1 * jax.random.normal(ks[0], (d, e))
    if gate_bias_to is not None:
        # force every token's softmax onto one expert
        gate = gate.at[:, gate_bias_to].add(gate_bias)
    return dict(
        gate_w=gate,
        w1=0.1 * jax.random.normal(ks[1], (e, d, m)),
        b1=jnp.zeros((e, m)),
        w2=0.1 * jax.random.normal(ks[2], (e, m, d)),
        b2=jnp.zeros((d,)),
    )


class TestRoutedFFN:
    def test_full_dispatch_matches_dense_mixture(self):
        """top_k=E with capacity >= S is exact dense MoE: the routed path
        must equal sum_e prob_e * MLP_e(x)."""
        d, e, m, b, s = 8, 4, 16, 2, 12
        p = _moe_params(jax.random.PRNGKey(0), d, e, m)
        x = jax.random.normal(jax.random.PRNGKey(1), (b, s, d))
        y, aux, drop = routed_ffn(x, p["gate_w"], p["w1"], p["b1"],
                                  p["w2"], p["b2"], top_k=e,
                                  capacity_factor=float(e))
        probs = jax.nn.softmax(x @ p["gate_w"], axis=-1)
        h1 = jax.nn.gelu(jnp.einsum("bsd,edf->bsef", x, p["w1"])
                         + p["b1"][None, None])
        dense = jnp.einsum("bsef,efd->bsed", h1, p["w2"])
        ref = jnp.einsum("bsed,bse->bsd", dense, probs) + p["b2"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        assert float(drop) == 0.0

    def test_skewed_routing_exact_drop_fraction_and_aux(self):
        """Every token wants expert 0: capacity keeps the first C tokens
        of each row, the rest are dropped — and the op SAYS so."""
        d, e, m, b, s = 8, 4, 16, 2, 64
        p = _moe_params(jax.random.PRNGKey(0), d, e, m, gate_bias_to=0)
        # positive tokens: the +10 column bias then dominates every
        # token's logit 0 (x @ (g0 + 10) ~ 10 * sum(x) > 0)
        x = jax.random.uniform(jax.random.PRNGKey(1), (b, s, d),
                               minval=0.5, maxval=1.5)
        cap = 16  # ceil(1.0 * 1 * 64 / 4)
        y, aux, drop = routed_ffn(x, p["gate_w"], p["w1"], p["b1"],
                                  p["w2"], p["b2"], top_k=1,
                                  capacity_factor=1.0)
        np.testing.assert_allclose(float(drop), 1.0 - cap / s, atol=1e-6)
        # balance loss ~ E when collapsed (vs ~1.0 balanced)
        assert float(aux) > 0.9 * e
        # kept tokens (first C of each row, priority = token order)
        # produce output; dropped tokens produce EXACT zero from the op
        norms = np.linalg.norm(np.asarray(y), axis=-1)
        assert (norms[:, :cap] > 1e-6).all()
        np.testing.assert_allclose(norms[:, cap:], 0.0, atol=1e-6)

    def test_balanced_routing_low_aux(self):
        d, e, m, b, s = 8, 4, 32, 4, 64
        p = _moe_params(jax.random.PRNGKey(3), d, e, m)
        x = jax.random.normal(jax.random.PRNGKey(4), (b, s, d))
        _, aux, drop = routed_ffn(x, p["gate_w"], p["w1"], p["b1"],
                                  p["w2"], p["b2"], top_k=2,
                                  capacity_factor=1.5)
        assert float(aux) < 1.3      # near 1.0 when balanced
        assert float(drop) < 0.15

    def test_aux_gradient_pushes_toward_balance(self):
        """d aux / d gate_w must be a real signal: one SGD step on the
        aux loss alone reduces it from a skewed start."""
        d, e, m, b, s = 8, 4, 16, 2, 32
        # mild skew: a saturated softmax would have a vanishing gradient
        p = _moe_params(jax.random.PRNGKey(0), d, e, m, gate_bias_to=0,
                        gate_bias=0.5)
        x = jax.random.uniform(jax.random.PRNGKey(1), (b, s, d),
                               minval=0.5, maxval=1.5)

        def aux_of(gate):
            return routed_ffn(x, gate, p["w1"], p["b1"], p["w2"], p["b2"],
                              top_k=2, capacity_factor=1.25)[1]

        a0, g = jax.value_and_grad(aux_of)(p["gate_w"])
        assert float(jnp.abs(g).max()) > 0.0
        a1 = aux_of(p["gate_w"] - 0.5 * g)
        assert float(a1) < float(a0)


class TestMoETransformerLayer:
    def _layer(self, **kw):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        kw.setdefault("hidden_drop", 0.0)
        kw.setdefault("attn_drop", 0.0)
        kw.setdefault("embedding_drop", 0.0)
        return TransformerLayer(vocab=32, seq_len=16, n_block=2, n_head=2,
                                hidden_size=16, moe_experts=4, moe_top_k=1,
                                moe_capacity_factor=1.0, **kw)

    def test_dropped_tokens_survive_via_residual(self):
        """The VERDICT r4 concern: at capacity, a degenerate router must
        not zero tokens at the BLOCK level.  Collapse the router post-init
        and check every output row keeps a healthy norm."""
        ly = self._layer()
        params = ly.init_params(jax.random.PRNGKey(0))
        for bp in params["blocks"]:
            # zero router -> all logits tie -> top_k picks expert 0 for
            # EVERY token (index tie-break): total collapse, input-free
            bp["moe_gate"] = jnp.zeros_like(bp["moe_gate"])
        tok = jnp.arange(16)[None, :].astype(jnp.int32).repeat(2, 0)
        out, st = ly.call(params, tok, training=False)
        # top_k=1, cf=1.0, E=4: capacity ceil(16/4)=4 of 16 -> 75% dropped
        np.testing.assert_allclose(float(st["moe_drop_fraction"]), 0.75,
                                   atol=1e-6)
        norms = np.linalg.norm(np.asarray(out), axis=-1)
        assert (norms > 1e-3).all()  # ...but no token was zeroed

    def test_state_structure_matches_init(self):
        ly = self._layer()
        params = ly.init_params(jax.random.PRNGKey(0))
        tok = jnp.zeros((2, 16), jnp.int32)
        _, st = ly.call(params, tok, training=True,
                        rng=jax.random.PRNGKey(1))
        init = ly.init_state()
        assert (jax.tree_util.tree_structure(st)
                == jax.tree_util.tree_structure(init))
        np.testing.assert_allclose(
            float(st["moe_aux_cost"]),
            0.01 * float(st["moe_aux_loss"]), rtol=1e-6)

    def test_moe_composes_with_remat(self):
        """jax.checkpoint around the block body must thread the routed
        FFN's aux outputs through the recompute unchanged."""
        ly = self._layer(remat="full")
        params = ly.init_params(jax.random.PRNGKey(0))
        tok = jnp.arange(16)[None, :].astype(jnp.int32).repeat(2, 0)

        def loss(p):
            out, st = ly.call(p, tok, training=True,
                              rng=jax.random.PRNGKey(1))
            return jnp.mean(out ** 2) + st["moe_aux_cost"], st

        (l, st), g = jax.value_and_grad(loss, has_aux=True)(params)
        assert np.isfinite(float(l))
        assert float(st["moe_aux_loss"]) > 0.0
        gate_g = g["blocks"][0]["moe_gate"]
        assert float(jnp.abs(gate_g).max()) > 0.0  # router still learns

        # remat off at identical params = identical forward
        ly2 = self._layer()
        out1, _ = ly.call(params, tok, training=False)
        out2, _ = ly2.call(params, tok, training=False)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   rtol=1e-6)

    def test_param_count_matches_tree(self):
        ly = self._layer()
        params = ly.init_params(jax.random.PRNGKey(0))
        n = sum(int(np.prod(x.shape))
                for x in jax.tree_util.tree_leaves(params))
        assert n == ly.param_count()

    def test_pipeline_builders_reject_moe(self):
        """The GPipe schedule would silently drop the aux loss; the stage
        builders must refuse MoE stacks outright."""
        from analytics_zoo_tpu.parallel.pipeline import (
            transformer_gpipe,
            transformer_gpipe_lm,
        )

        ly = self._layer()
        params = ly.init_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="dense blocks only"):
            transformer_gpipe(ly, params, jnp.zeros((2, 16, 16)),
                              n_microbatch=2)
        with pytest.raises(ValueError, match="dense blocks only"):
            transformer_gpipe_lm(ly, params, jnp.zeros((16, 32)),
                                 jnp.zeros((32,)),
                                 jnp.zeros((2, 16), jnp.int32),
                                 n_microbatch=2)

    def test_strategies_steps_include_aux(self):
        """make_shard_map_train_step must also add the state-channel aux
        cost — every model.forward-based loss does, not just the
        estimator's (review finding, round 5)."""
        import optax

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.strategies import (
            make_shard_map_train_step,
        )
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.api.keras.objectives import (
            get_loss,
        )

        zoo.init_zoo_context(seed=5, mesh_shape={"data": 8})
        m = Sequential()
        m.add(self._layer(input_shape=(16,)))
        m.add(Flatten())
        m.add(Dense(2, activation="softmax"))
        params, state = m.build_params()
        loss_fn = get_loss("sparse_categorical_crossentropy")
        opt = optax.sgd(0.0)  # lr 0: params unchanged, loss comparable
        step = make_shard_map_train_step(m, loss_fn, opt)

        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, size=(16, 16)).astype(np.int32)
        y = rng.integers(0, 2, size=(16,)).astype(np.int32)
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        p2, _, new_state, l = step(params, opt.init(params), state,
                                   jax.random.PRNGKey(0), batch)
        preds, st2 = m.forward(p2, batch["x"], state=state, training=True,
                               rng=jax.random.PRNGKey(0))
        task = float(loss_fn.mean(batch["y"], preds))
        aux_cost = [float(v["moe_aux_cost"]) for v in st2.values()
                    if isinstance(v, dict) and "moe_aux_cost" in v][0]
        assert aux_cost > 0.0
        np.testing.assert_allclose(float(l), task + aux_cost, rtol=1e-5)

    def test_shard_map_step_with_expert_axis_runs(self):
        """Review finding (r5): with an expert axis in the mesh, the
        sharding constraint inside routed_ffn must not blow up the
        shard_map train steps (manual axes reject constraints — the op
        falls back to shard-local compute there)."""
        import optax

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.strategies import (
            make_shard_map_train_step,
        )
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.api.keras.objectives import (
            get_loss,
        )

        zoo.init_zoo_context(seed=5, mesh_shape={"data": 4, "expert": 2},
                             mesh_axes=("data", "expert"))
        m = Sequential()
        m.add(self._layer(input_shape=(16,)))
        m.add(Flatten())
        m.add(Dense(2, activation="softmax"))
        params, state = m.build_params()
        opt = optax.sgd(0.1)
        step = make_shard_map_train_step(
            m, get_loss("sparse_categorical_crossentropy"), opt)
        rng = np.random.default_rng(0)
        batch = {"x": jnp.asarray(
            rng.integers(0, 32, size=(8, 16)).astype(np.int32)),
            "y": jnp.asarray(rng.integers(0, 2, size=(8,))
                             .astype(np.int32))}
        p2, _, _, l = step(params, opt.init(params), state,
                           jax.random.PRNGKey(0), batch)
        assert np.isfinite(float(l))

    def test_fit_includes_aux_and_learns(self):
        """End to end through the estimator: the training loss includes
        the pre-weighted aux cost, and a tiny copy task still learns."""
        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Flatten,
        )

        zoo.init_zoo_context(seed=11)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, size=(128, 16)).astype(np.int32)
        y = (x[:, 0] % 2).astype(np.int32)  # depends on token 0 identity

        m = Sequential()
        m.add(self._layer(input_shape=(16,)))
        m.add(Flatten())
        m.add(Dense(2, activation="softmax"))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=8)
        ev = m.evaluate(x, y)
        assert ev["accuracy"] > 0.8, ev
        # the stack's state leaves surfaced through fit
        st = m.state
        (tl_state,) = [v for k, v in st.items() if "moe_aux_loss" in v]
        assert float(tl_state["moe_aux_loss"]) > 0.0
