"""TorchNet / TFNet / Net facade tests (reference pyzoo test suites for
torch_net and tfnet; SURVEY.md §2.1 TFNet/TorchNet rows)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


rng0 = np.random.default_rng(0)


def test_torchnet_forward_matches_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    mod = torch.nn.Sequential(
        torch.nn.Linear(6, 4), torch.nn.ReLU(), torch.nn.Linear(4, 3)
    )
    net = TorchNet.from_pytorch(mod, input_shape=(6,))
    x = rng0.normal(size=(5, 6)).astype(np.float32)

    net.ensure_built((6,))
    out, _ = net.apply({}, jnp.asarray(x))
    with torch.no_grad():
        ref = mod(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)
    assert net.compute_output_shape((5, 6)) == (5, 3)


def test_torchnet_input_gradient():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    mod = torch.nn.Linear(4, 2)
    net = TorchNet.from_pytorch(mod, input_shape=(4,))
    net.ensure_built((4,))
    x = rng0.normal(size=(3, 4)).astype(np.float32)

    def f(xx):
        return jnp.sum(net.call({}, xx) ** 2)

    g = np.asarray(jax.grad(f)(jnp.asarray(x)))

    xt = torch.from_numpy(x).requires_grad_(True)
    (mod(xt) ** 2).sum().backward()
    np.testing.assert_allclose(g, xt.grad.numpy(), rtol=1e-4, atol=1e-5)


def test_torchnet_in_sequential_predict():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    mod = torch.nn.Linear(5, 4)
    m = Sequential()
    m.add(TorchNet.from_pytorch(mod, input_shape=(5,)))
    m.add(Dense(2))
    x = rng0.normal(size=(8, 5)).astype(np.float32)
    out = np.asarray(m.predict(x, batch_size=8))
    assert out.shape == (8, 2)


def test_torchnet_save_load(tmp_path):
    import torch

    from analytics_zoo_tpu.pipeline.api.net import Net, TorchNet

    mod = torch.nn.Linear(3, 2)
    net = TorchNet.from_pytorch(mod, input_shape=(3,))
    p = str(tmp_path / "m.pt")
    net.save(p)

    net2 = Net.load_torch(p, input_shape=(3,))
    x = rng0.normal(size=(2, 3)).astype(np.float32)
    net.ensure_built((3,))
    net2.ensure_built((3,))
    a, _ = net.apply({}, jnp.asarray(x))
    b, _ = net2.apply({}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_torch_criterion_trains_direction():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchCriterion

    crit = TorchCriterion.from_pytorch(torch.nn.MSELoss())
    y_true = jnp.asarray(rng0.normal(size=(4, 3)).astype(np.float32))
    y_pred = jnp.asarray(rng0.normal(size=(4, 3)).astype(np.float32))

    val = crit(y_true, y_pred)
    ref = float(np.mean((np.asarray(y_true) - np.asarray(y_pred)) ** 2))
    assert float(val) == pytest.approx(ref, rel=1e-5)

    g = jax.grad(lambda p: crit(y_true, p))(y_pred)
    ref_g = 2.0 / y_pred.size * (np.asarray(y_pred) - np.asarray(y_true))
    np.testing.assert_allclose(np.asarray(g), ref_g, rtol=1e-4, atol=1e-6)


def test_import_state_dict():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.net import import_state_dict

    mod = torch.nn.Linear(4, 3)
    m = Sequential()
    m.add(Dense(3, input_shape=(4,)))
    m.build_params()
    (dense_name,) = list(m.params)

    import_state_dict(
        m, mod.state_dict(),
        [(f"{dense_name}/kernel", "weight", lambda a: a.T),
         (f"{dense_name}/bias", "bias", None)],
    )
    x = rng0.normal(size=(2, 4)).astype(np.float32)
    out = np.asarray(m.predict(x, batch_size=2))
    with torch.no_grad():
        ref = mod(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="module")
def tf():
    return pytest.importorskip("tensorflow")


def test_tfnet_from_keras_and_gradient(tf):
    from analytics_zoo_tpu.pipeline.api.net import TFNet

    km = tf.keras.Sequential([
        tf.keras.layers.Dense(4, activation="relu"),
        tf.keras.layers.Dense(2),
    ])
    km.build((None, 6))
    net = TFNet.from_keras(km, input_shape=(6,))
    net.ensure_built((6,))

    x = rng0.normal(size=(3, 6)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    ref = km(x, training=False).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    g = np.asarray(jax.grad(
        lambda xx: jnp.sum(net.call({}, xx) ** 2)
    )(jnp.asarray(x)))
    xt = tf.convert_to_tensor(x)
    with tf.GradientTape() as tape:
        tape.watch(xt)
        y = tf.reduce_sum(km(xt) ** 2)
    ref_g = tape.gradient(y, xt).numpy()
    np.testing.assert_allclose(g, ref_g, rtol=1e-4, atol=1e-5)


def test_tfnet_saved_model_roundtrip(tf, tmp_path):
    from analytics_zoo_tpu.pipeline.api.net import Net

    km = tf.keras.Sequential([tf.keras.layers.Dense(3)])
    km.build((None, 5))
    d = str(tmp_path / "sm")

    @tf.function(input_signature=[tf.TensorSpec([None, 5], tf.float32)])
    def serve(x):
        return km(x)

    tf.saved_model.save(km, d, signatures=serve)

    net = Net.load_tf(d, input_shape=(5,))
    net.ensure_built((5,))
    x = rng0.normal(size=(2, 5)).astype(np.float32)
    out, _ = net.apply({}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), km(x).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_torchnet_shape_dependent_output():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    # fully-convolutional: output spatial size tracks input spatial size
    mod = torch.nn.Conv2d(1, 2, 3, padding=1)

    class NHWC(torch.nn.Module):
        def forward(self, x):
            return mod(x.permute(0, 3, 1, 2)).permute(0, 2, 3, 1)

    net = TorchNet.from_pytorch(NHWC())
    net.ensure_built((8, 8, 1))
    a, _ = net.apply({}, jnp.zeros((2, 8, 8, 1), jnp.float32))
    b, _ = net.apply({}, jnp.zeros((2, 16, 16, 1), jnp.float32))
    assert np.asarray(a).shape == (2, 8, 8, 2)
    assert np.asarray(b).shape == (2, 16, 16, 2)


def test_torchnet_no_grad_path_zero_gradinput():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchNet

    class Detached(torch.nn.Module):
        def forward(self, x):
            return x.detach() * 2.0

    net = TorchNet.from_pytorch(Detached(), input_shape=(4,))
    net.ensure_built((4,))
    x = jnp.ones((2, 4), jnp.float32)
    g = jax.grad(lambda xx: jnp.sum(net.call({}, xx)))(x)
    np.testing.assert_array_equal(np.asarray(g), np.zeros((2, 4)))


def test_torch_criterion_reduction_none():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchCriterion

    crit = TorchCriterion.from_pytorch(
        torch.nn.MSELoss(reduction="none")
    )
    y_true = jnp.asarray(rng0.normal(size=(4, 3)).astype(np.float32))
    y_pred = jnp.asarray(rng0.normal(size=(4, 3)).astype(np.float32))
    val = float(crit(y_true, y_pred))
    ref = float(np.mean((np.asarray(y_true) - np.asarray(y_pred)) ** 2))
    assert val == pytest.approx(ref, rel=1e-5)


def test_import_state_dict_rejects_nothing_silently():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.net import import_state_dict

    mod = torch.nn.Linear(4, 3)
    m = Sequential()
    m.add(Dense(3, input_shape=(4,)))
    m.build_params()
    (dense_name,) = list(m.params)
    before = np.asarray(m.params[dense_name]["bias"]).copy()
    import_state_dict(m, mod.state_dict(),
                      [(f"{dense_name}/bias", "bias", None)])
    after = np.asarray(m.params[dense_name]["bias"])
    np.testing.assert_allclose(after, mod.bias.detach().numpy(), atol=1e-6)
    assert not np.allclose(before, after)


def test_keras2_global_pool_model_pickles(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras2 import Sequential, layers as k2

    m = Sequential()
    m.add(k2.Conv2D(2, 3, input_shape=(6, 6, 1)))
    m.add(k2.GlobalAveragePooling2D())
    x = rng0.normal(size=(2, 6, 6, 1)).astype(np.float32)
    ref = np.asarray(m.predict(x, batch_size=2))

    p = str(tmp_path / "m.zoo")
    m.save(p)
    from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

    m2 = KerasNet.load(p)
    np.testing.assert_allclose(
        np.asarray(m2.predict(x, batch_size=2)), ref, atol=1e-6
    )


def test_keras2_rejects_nonzero_bias_init():
    from analytics_zoo_tpu.pipeline.api.keras2 import layers as k2

    with pytest.raises(ValueError, match="zero bias"):
        k2.Dense(4, bias_initializer="ones")


def test_torch_criterion_rejects_sample_weight():
    import torch

    from analytics_zoo_tpu.pipeline.api.net import TorchCriterion

    crit = TorchCriterion.from_pytorch(torch.nn.MSELoss())
    y = jnp.zeros((2, 3))
    with pytest.raises(NotImplementedError, match="sample_weight"):
        crit.mean(y, y, sample_weight=jnp.ones((2,)))


def test_tfnet_scalar_output_shape_hint():
    tf = pytest.importorskip("tensorflow")

    from analytics_zoo_tpu.pipeline.api.net import TFNet

    calls = []

    def fn(x):
        calls.append(x.shape)
        return tf.reduce_sum(x, axis=list(range(1, len(x.shape))))

    net = TFNet(fn, output_shape=(), input_shape=(4,))
    net.ensure_built((4,))
    assert calls == []  # explicit () hint suppresses the probe
    out, _ = net.apply({}, jnp.ones((3, 4), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [4.0, 4.0, 4.0])
