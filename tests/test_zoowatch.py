"""zoowatch federation plane (ISSUE 17): time-series windows, SLO
burn-rate engine, cross-host scraping, federated scaling signals, the
supervisor's heartbeat SLO, flight-dump merging, and the metrics-docs
drift gate — plus the two acceptance bench guards.

Alphabetically this file sorts AFTER the tier-1 timeout horizon, so the
heavy e2e guards at the bottom run in the quick tier (conftest
QUICK_FILES) and nightly, like test_fleet.py's scaling guard."""

import json
import math
import os
import re
import socket
import sys
import threading
import time
import urllib.request

import pytest

from analytics_zoo_tpu.metrics import MetricsRegistry
from analytics_zoo_tpu.metrics.merge import (
    TelemetryAggregator,
    registry_samples,
)
from analytics_zoo_tpu.metrics.slo import (
    SloEngine,
    SloSpec,
    alertz_doc,
    default_slos,
)
from analytics_zoo_tpu.metrics.timeseries import (
    TimeSeriesStore,
    fraction_le,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)


def _counter_sample(name, value, labels=None):
    s = {"name": name, "kind": "counter", "value": float(value)}
    if labels:
        s["labels"] = labels
    return s


def _gauge_sample(name, value, labels=None):
    s = {"name": name, "kind": "gauge", "value": float(value)}
    if labels:
        s["labels"] = labels
    return s


def _hist_samples(name, observations, buckets=(0.1, 0.5, 1.0)):
    """Mergeable-format histogram sample via a REAL registry — the
    exact shape the scraper pulls off /telemetryz."""
    reg = MetricsRegistry()
    h = reg.histogram(name, "", buckets=buckets)
    for v in observations:
        h.observe(v)
    return [s for s in registry_samples(reg) if s["name"] == name]


# ---------------------------------------------------------------------------
# TimeSeriesStore
# ---------------------------------------------------------------------------


class TestTimeSeriesStore:
    def test_capacity_needs_two_edges(self):
        with pytest.raises(ValueError, match="capacity"):
            TimeSeriesStore(capacity=1)

    def test_counter_rate(self):
        st = TimeSeriesStore()
        st.ingest([_counter_sample("zoo_x_total", 0)], ts=100.0)
        st.ingest([_counter_sample("zoo_x_total", 50)], ts=110.0)
        assert st.rate("zoo_x_total", 20.0, now=110.0) == \
            pytest.approx(5.0)
        # single point in window: no rate
        assert st.rate("zoo_x_total", 1.0, now=110.0) == 0.0

    def test_counter_reset_degrades_not_negative(self):
        st = TimeSeriesStore()
        st.ingest([_counter_sample("zoo_x_total", 50)], ts=100.0)
        st.ingest([_counter_sample("zoo_x_total", 10)], ts=110.0)
        # reset mid-window: increase becomes the newest value, never <0
        assert st.rate("zoo_x_total", 20.0, now=110.0) == \
            pytest.approx(1.0)

    def test_rate_aggregates_across_hosts(self):
        st = TimeSeriesStore()
        for host in ("h1", "h2"):
            st.ingest([_counter_sample("zoo_x_total", 0)], ts=0.0,
                      source={"host": host})
            st.ingest([_counter_sample("zoo_x_total", 10)], ts=10.0,
                      source={"host": host})
        assert st.rate("zoo_x_total", 20.0, now=10.0) == \
            pytest.approx(2.0)
        # exact-label query selects one series
        assert st.rate("zoo_x_total", 20.0, labels={"host": "h1"},
                       now=10.0) == pytest.approx(1.0)

    def test_window_summary_sees_only_window(self):
        st = TimeSeriesStore()
        st.ingest(_hist_samples("zoo_h", [0.05] * 100), ts=100.0)
        st.ingest(_hist_samples("zoo_h", [0.05] * 100 + [0.9] * 10),
                  ts=110.0)
        summ = st.window_summary("zoo_h", 15.0, now=110.0)
        assert summ["count"] == 10  # the delta, not the lifetime 110
        assert 0.5 < summ["p50"] <= 1.0
        # empty window -> zero summary, no crash
        assert st.window_summary("zoo_h", 15.0, now=500.0)["count"] == 0

    def test_window_summary_merges_hosts_bucketwise(self):
        st = TimeSeriesStore()
        for host in ("h1", "h2"):
            st.ingest(_hist_samples("zoo_h", [0.05]), ts=100.0,
                      source={"host": host})
            st.ingest(_hist_samples("zoo_h", [0.05, 0.9, 0.9]),
                      ts=110.0, source={"host": host})
        summ = st.window_summary("zoo_h", 15.0, now=110.0)
        assert summ["count"] == 4  # (3-1) per host, summed

    def test_percentile_over_supported_quantiles_only(self):
        st = TimeSeriesStore()
        st.ingest(_hist_samples("zoo_h", [0.05]), ts=0.0)
        st.ingest(_hist_samples("zoo_h", [0.05, 0.05]), ts=1.0)
        assert st.percentile_over("zoo_h", 0.99, 10.0, now=1.0) <= 0.1
        with pytest.raises(ValueError, match="percentile_over"):
            st.percentile_over("zoo_h", 0.9, 10.0, now=1.0)

    def test_bad_fraction_gauge_points(self):
        st = TimeSeriesStore()
        st.observe("zoo_age", 1.0, ts=100.0)
        st.observe("zoo_age", 20.0, ts=101.0)
        bad, n = st.bad_fraction("zoo_age", 10.0, 5.0, now=101.0)
        assert n == 2 and bad == pytest.approx(0.5)

    def test_bad_fraction_histogram(self):
        st = TimeSeriesStore()
        st.ingest(_hist_samples("zoo_h", [0.05]), ts=100.0)
        st.ingest(_hist_samples("zoo_h", [0.05] * 10 + [0.9]),
                  ts=110.0)
        bad, n = st.bad_fraction("zoo_h", 0.5, 15.0, now=110.0)
        assert n == 10 and bad == pytest.approx(0.1, abs=1e-6)

    def test_burn_rate_semantics(self):
        st = TimeSeriesStore()
        with pytest.raises(ValueError, match="objective"):
            st.burn_rate("zoo_age", 1.0, 1.5, 10.0)
        # no data is not a violation
        assert st.burn_rate("zoo_age", 1.0, 0.9, 10.0, now=0.0) == 0.0
        st.observe("zoo_age", 5.0, ts=100.0)  # 100% bad, budget 10%
        assert st.burn_rate("zoo_age", 1.0, 0.9, 10.0, now=100.0) == \
            pytest.approx(10.0)

    def test_max_series_bound_counts_drops(self):
        st = TimeSeriesStore(max_series=1)
        st.ingest([_gauge_sample("zoo_a", 1), _gauge_sample("zoo_b", 1)],
                  ts=0.0)
        assert len(st.series()) == 1
        assert st.dropped_series == 1

    def test_ring_capacity_bounds_points(self):
        st = TimeSeriesStore(capacity=4)
        for i in range(10):
            st.observe("zoo_g", float(i), ts=float(i))
        assert next(iter(st.series().values()))["points"] == 4


class TestFractionLe:
    def test_empty_window_is_all_good(self):
        assert fraction_le((1.0,), [0, 0], 0.5) == 1.0

    def test_interpolates_inside_bucket(self):
        # 10 observations uniform in (0, 1]; threshold mid-bucket
        assert fraction_le((1.0,), [10, 0], 0.5) == pytest.approx(0.5)

    def test_threshold_above_all_bounds(self):
        assert fraction_le((1.0,), [5, 0], 2.0) == 1.0


# ---------------------------------------------------------------------------
# SloSpec / SloEngine
# ---------------------------------------------------------------------------


class TestSloSpec:
    def test_validation(self):
        ok = dict(name="s", family="f", threshold=1.0)
        SloSpec(**ok)
        with pytest.raises(ValueError, match="objective"):
            SloSpec(**dict(ok, objective=1.0))
        with pytest.raises(ValueError, match="threshold"):
            SloSpec(**dict(ok, threshold=0.0))
        with pytest.raises(ValueError, match="short_window"):
            SloSpec(**dict(ok, short_window=60.0, long_window=30.0))
        with pytest.raises(ValueError, match="kind"):
            SloSpec(**dict(ok, kind="gauge"))
        with pytest.raises(ValueError, match="burn_threshold"):
            SloSpec(**dict(ok, burn_threshold=0.0))

    def test_default_slos_cover_the_stock_planes(self):
        specs = {s.name: s for s in default_slos()}
        assert set(specs) == {"predict_latency", "step_time",
                              "checkpoint_stall", "worker_heartbeat"}
        # host liveness rides the scraper's own staleness gauge
        hb = specs["worker_heartbeat"]
        assert hb.family == "zoo_scrape_staleness_seconds"
        assert hb.kind == "ceiling"


class _FakeFlight:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append(dict(kind=kind, **fields))


class TestSloEngine:
    def _spec(self):
        return SloSpec("age", "zoo_age", threshold=1.0, objective=0.9,
                       kind="ceiling", short_window=10.0,
                       long_window=20.0, burn_threshold=1.0)

    def test_fire_and_resolve_transitions(self):
        st = TimeSeriesStore()
        reg = MetricsRegistry()
        fl = _FakeFlight()
        eng = SloEngine(st, [self._spec()], registry=reg, flight=fl)
        for ts in (990.0, 995.0, 1000.0):
            st.observe("zoo_age", 5.0, ts=ts)  # all above threshold
        firing = eng.evaluate(now=1000.0)
        assert len(firing) == 1
        a = firing[0]
        assert a["slo"] == "age" and a["firing"]
        assert a["short_burn"] >= 1.0 and a["long_burn"] >= 1.0
        assert a["since"] == 1000.0
        # burn gauges + alert counter landed in the registry
        txt = {s["name"]: s for s in registry_samples(reg)
               if s.get("labels", {}).get("slo") == "age"}
        assert "zoo_slo_burn_rate" in txt
        assert txt["zoo_slo_alert_active"]["value"] == 1.0
        # "since" survives continued firing
        assert eng.evaluate(now=1001.0)[0]["since"] == 1000.0
        # an empty window resolves the alert
        assert eng.evaluate(now=2000.0) == []
        states = [d["state"] for d in eng.decision_log()]
        assert states == ["firing", "resolved"]
        assert [e["state"] for e in fl.events
                if e["kind"] == "slo_alert"] == ["firing", "resolved"]

    def test_alertz_doc_rolls_up_live_engines(self):
        st = TimeSeriesStore()
        eng = SloEngine(st, [self._spec()])
        st.observe("zoo_age", 5.0, ts=100.0)
        eng.evaluate(now=100.0)
        doc = alertz_doc()
        assert doc["engines"] >= 1
        assert any(a["slo"] == "age" and a["firing"]
                   for a in doc["firing"])

    def test_to_doc_shape(self):
        eng = SloEngine(TimeSeriesStore(), [self._spec()])
        eng.evaluate(now=0.0)
        doc = eng.to_doc()
        assert {s["name"] for s in doc["specs"]} == {"age"}
        assert doc["alerts"][0]["firing"] is False
        assert doc["decisions"] == []


# ---------------------------------------------------------------------------
# TelemetryAggregator staleness
# ---------------------------------------------------------------------------


class TestAggregatorStaleness:
    def test_stale_flagging_and_label(self):
        agg = TelemetryAggregator(stale_after=0.05)
        agg.ingest({"ts": time.time(),
                    "samples": [_counter_sample("zoo_c_total", 3)]},
                   host="h1")
        src = agg.sources()
        key = next(iter(src))
        assert src[key]["stale"] is False
        assert src[key]["age_seconds"] >= 0.0
        time.sleep(0.08)
        assert agg.sources()[key]["stale"] is True
        assert agg.stale_sources() == [key]
        labeled = [s for s in agg.labeled_samples()
                   if s["name"] == "zoo_c_total"]
        assert labeled and all(
            s["labels"].get("stale") == "true" for s in labeled)


# ---------------------------------------------------------------------------
# VarzScraper
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestScraperTargets:
    def test_normalize_target(self):
        from analytics_zoo_tpu.metrics.scrape import normalize_target

        assert normalize_target("127.0.0.1:9090") == \
            ("127.0.0.1:9090", "http://127.0.0.1:9090")
        assert normalize_target("http://h:1/varz") == \
            ("h:1", "http://h:1")
        assert normalize_target(("r1", "http://h:2/")) == \
            ("r1", "http://h:2")

    def test_targets_from_env(self):
        from analytics_zoo_tpu.metrics.scrape import targets_from_env

        got = targets_from_env(
            {"ZOO_SCRAPE_TARGETS": "a:1, b:2 http://c:3"})
        assert [n for n, _ in got] == ["a:1", "b:2", "c:3"]
        assert targets_from_env({}) == []


class TestVarzScraper:
    def _server(self, reg):
        from analytics_zoo_tpu.metrics import MetricsServer

        return MetricsServer(port=0, host="127.0.0.1",
                             registry=reg).start()

    def test_scrapes_live_server_into_store_and_aggregator(self):
        from analytics_zoo_tpu.metrics.health import HealthRegistry
        from analytics_zoo_tpu.metrics.scrape import VarzScraper

        reg = MetricsRegistry()
        reg.counter("zoo_demo_total", "").inc(3)
        reg.histogram("zoo_demo_seconds", "",
                      buckets=(0.1, 1.0)).observe(0.05)
        srv = self._server(reg)
        st = TimeSeriesStore()
        agg = TelemetryAggregator()
        sc = VarzScraper(targets=[("r1", srv.url)], store=st,
                         aggregator=agg, interval=0.1,
                         health=HealthRegistry())
        try:
            assert sc.poll_once() == 1
            hz = sc.healthz()
            assert hz["healthy"] is True
            assert hz["targets"]["r1"]["fetches"] == 1
            # per-host series landed, labeled by target
            assert st.label_sets("zoo_demo_total") == [{"host": "r1"}]
            # histograms survive (mergeable /telemetryz, not /varz)
            assert st.label_sets("zoo_demo_seconds")
            # the scraper's own staleness series feeds the stock SLO
            assert st.label_sets("zoo_scrape_staleness_seconds") == \
                [{"target": "r1"}]
            assert agg.sources()
        finally:
            srv.stop()

    def test_dead_target_stays_visible_and_unhealthy(self):
        from analytics_zoo_tpu.metrics.health import HealthRegistry
        from analytics_zoo_tpu.metrics.scrape import VarzScraper

        sc = VarzScraper(
            targets=[f"127.0.0.1:{_free_port()}"],
            store=TimeSeriesStore(), interval=0.1, timeout=0.5,
            health=HealthRegistry())
        assert sc.poll_once() == 0
        hz = sc.healthz()
        assert hz["healthy"] is False
        tgt = next(iter(hz["targets"].values()))
        assert tgt["errors"] == 1 and tgt["last_error"]
        assert tgt["age_seconds"] is None

    def test_empty_target_set_is_not_healthy(self):
        from analytics_zoo_tpu.metrics.health import HealthRegistry
        from analytics_zoo_tpu.metrics.scrape import VarzScraper

        sc = VarzScraper(health=HealthRegistry())
        assert sc.healthz()["healthy"] is False

    def test_discovery_merges_dynamic_targets(self):
        from analytics_zoo_tpu.metrics.health import HealthRegistry
        from analytics_zoo_tpu.metrics.scrape import VarzScraper

        reg = MetricsRegistry()
        srv = self._server(reg)
        sc = VarzScraper(store=TimeSeriesStore(), interval=0.1,
                         health=HealthRegistry(),
                         discover=lambda: {"rep-0": srv.url})
        try:
            sc.poll_once()
            assert sc.targets() == ["rep-0"]
            assert sc.healthz()["targets"]["rep-0"]["static"] is False
        finally:
            srv.stop()

    def test_varz_fallback_drops_unmergeable_histograms(self):
        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/telemetryz":  # predates the route
                    self.send_error(404)
                    return
                body = json.dumps({"ts": time.time(), "samples": [
                    _counter_sample("zoo_old_total", 2),
                    {"name": "zoo_old_seconds", "kind": "histogram",
                     "sum": 1.0, "count": 2},  # summary: unmergeable
                ]}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        from analytics_zoo_tpu.metrics.health import HealthRegistry
        from analytics_zoo_tpu.metrics.scrape import VarzScraper

        st = TimeSeriesStore()
        sc = VarzScraper(
            targets=[f"127.0.0.1:{httpd.server_address[1]}"],
            store=st, interval=0.1, health=HealthRegistry())
        try:
            assert sc.poll_once() == 1
            assert st.label_sets("zoo_old_total")
            assert not st.label_sets("zoo_old_seconds")
        finally:
            httpd.shutdown()

    def test_fleet_discovery_reads_broker_published_urls(self):
        from analytics_zoo_tpu.metrics.scrape import (
            VARZ_KEY_PREFIX,
            fleet_varz_targets,
        )
        from analytics_zoo_tpu.serving.broker import connect_broker

        b = connect_broker("memory")
        b.hset(VARZ_KEY_PREFIX + "rep-3",
               {"url": "http://127.0.0.1:7777", "ts": time.time()})
        assert fleet_varz_targets(b)() == \
            {"rep-3": "http://127.0.0.1:7777"}


# ---------------------------------------------------------------------------
# /telemetryz + /alertz endpoints
# ---------------------------------------------------------------------------


class TestHttpEndpoints:
    def test_telemetryz_serves_mergeable_snapshot(self):
        from analytics_zoo_tpu.metrics import MetricsServer

        reg = MetricsRegistry()
        reg.histogram("zoo_h_seconds", "",
                      buckets=(0.1, 1.0)).observe(0.05)
        srv = MetricsServer(port=0, host="127.0.0.1",
                            registry=reg).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                srv.url + "/telemetryz", timeout=10).read())
            hist = [s for s in doc["samples"]
                    if s["name"] == "zoo_h_seconds"]
            assert hist and hist[0]["buckets"]  # bucket vectors kept
        finally:
            srv.stop()

    def test_alertz_serves_live_engine_state(self):
        from analytics_zoo_tpu.metrics import MetricsServer

        st = TimeSeriesStore()
        eng = SloEngine(st, [SloSpec(
            "age", "zoo_age", threshold=1.0, objective=0.9,
            kind="ceiling", short_window=10.0, long_window=20.0)])
        st.observe("zoo_age", 5.0, ts=time.time())
        eng.evaluate()
        srv = MetricsServer(port=0, host="127.0.0.1",
                            registry=MetricsRegistry()).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                srv.url + "/alertz", timeout=10).read())
            assert {"ts", "engines", "firing", "alerts"} <= set(doc)
            assert any(a["slo"] == "age" for a in doc["alerts"])
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# clock anchors + flight merging (the explainability satellites)
# ---------------------------------------------------------------------------


class TestClockAnchors:
    def test_tracer_anchor_maps_trace_zero_to_both_clocks(self):
        from analytics_zoo_tpu.metrics import Tracer

        t = Tracer()
        a = t.clock_anchor()
        assert abs(a["epoch"] - time.time()) < 5.0
        assert abs(a["monotonic"] - time.monotonic()) < 5.0
        assert t.to_chrome_trace()["metadata"]["clock_anchor"] == \
            pytest.approx(a)

    def test_flight_events_carry_monotonic_next_to_epoch(self):
        from analytics_zoo_tpu.metrics.flight import FlightRecorder

        fr = FlightRecorder(capacity=8)
        fr.record("step", step=1)
        doc = fr.to_doc("test")
        assert doc["reason"] == "test" and doc["pid"] == os.getpid()
        assert {"epoch", "monotonic"} <= set(doc["clock_anchor"])
        ev = doc["events"][-1]
        assert "mono" in ev and "ts" in ev
        assert abs((ev["ts"] - ev["mono"])
                   - (time.time() - time.monotonic())) < 5.0


def _flight_doc(pid, reason, events, skew_s=0.0):
    """Fabricated dump: ``skew_s`` shifts THIS process's wall clock
    while the shared monotonic clock stays truthful."""
    return {
        "reason": reason, "pid": pid, "dropped_events": 0,
        "clock_anchor": {"epoch": 1000.0 + skew_s, "monotonic": 0.0},
        "events": [dict(e, ts=1000.0 + skew_s + e["mono"])
                   for e in events],
    }


class TestFlightMerge:
    def _merge(self):
        _tools()
        import flight_merge

        return flight_merge

    def test_skewed_source_corrected_onto_cohort_clock(self):
        fm = self._merge()
        docs = [
            _flight_doc(100, "exit", [
                {"kind": "elastic", "event": "chaos", "mono": 10.0},
                {"kind": "elastic", "event": "respawn", "mono": 12.0},
            ]),
            # +5s wall-clock skew; its event REALLY happened at mono 11
            _flight_doc(200, "exit", [
                {"kind": "elastic", "event": "leave", "mono": 11.0},
            ], skew_s=5.0),
            _flight_doc(300, "exit", [
                {"kind": "elastic", "event": "join", "mono": 13.0},
            ]),
        ]
        merged = fm.merge_flight_docs(docs, skew_tolerance_s=0.25)
        assert merged["sources"] == 3
        assert merged["skew"]["200@exit"]["offset_s"] == \
            pytest.approx(5.0)
        assert merged["skew"]["200@exit"]["beyond_tolerance"] is True
        assert merged["skew"]["100@exit"]["beyond_tolerance"] is False
        # corrected ordering: chaos < leave < respawn < join
        assert [e["event"] for e in merged["timeline"]] == \
            ["chaos", "leave", "respawn", "join"]
        lines = fm.narrative_lines(merged)
        assert len(lines) == 4 and "chaos" in lines[0]

    def test_merged_chrome_trace_places_anchored_spans(self):
        fm = self._merge()
        merged = fm.merge_flight_docs([_flight_doc(100, "exit", [
            {"kind": "elastic", "event": "chaos", "mono": 10.0}])])
        trace = {"traceEvents": [
            {"name": "step", "ph": "X", "ts": 0.0, "dur": 5.0,
             "pid": 100, "tid": 1}],
            "metadata": {"clock_anchor": {"epoch": 1012.0,
                                          "monotonic": 12.0}}}
        out = fm.merged_chrome_trace(merged, [trace])
        span = [e for e in out["traceEvents"] if e["ph"] == "X"][0]
        # flight t0 = 1010.0; the span's trace-0 = epoch 1012 -> +2s
        assert span["ts"] == pytest.approx(2e6)
        assert out["metadata"]["sources"] == 1

    def test_main_returns_2_when_no_dumps(self, tmp_path):
        fm = self._merge()
        assert fm.main([str(tmp_path)]) == 2


# ---------------------------------------------------------------------------
# federated scaler path
# ---------------------------------------------------------------------------


class TestDecideFleet:
    def _hot(self):
        # est p99 = 0.12s vs a 0.1s SLO: a 1.2x proportional step
        from analytics_zoo_tpu.serving.scaler import FleetSignals

        return FleetSignals(predict_p99_s=0.12, window_count=50,
                            service_rate=10.0, queue_depth=0)

    def test_host_target_is_the_packing_consequence(self):
        from analytics_zoo_tpu.serving.scaler import SloScaler

        sc = SloScaler(slo_p99_ms=100.0, min_replicas=1,
                       max_replicas=8, up_windows=1)
        target, hosts, reason = sc.decide_fleet(2, 1, self._hot())
        assert target == 3 and reason == "slo_violation"
        assert hosts == 2  # rph = ceil(2/1) = 2 -> ceil(3/2)

    def test_explicit_packing_and_max_hosts(self):
        from analytics_zoo_tpu.serving.scaler import SloScaler

        sc = SloScaler(slo_p99_ms=100.0, min_replicas=1,
                       max_replicas=8, up_windows=1)
        target, hosts, _ = sc.decide_fleet(
            4, 2, self._hot(), replicas_per_host=1, max_hosts=3)
        assert target == 5 and hosts == 3  # capped below ceil(5/1)

    def test_idle_fleet_holds(self):
        from analytics_zoo_tpu.serving.scaler import (
            FleetSignals,
            SloScaler,
        )

        sc = SloScaler(slo_p99_ms=100.0)
        target, hosts, _ = sc.decide_fleet(2, 2, FleetSignals())
        assert (target, hosts) == (2, 2)  # rph=1: packing is kept


class _FakeBroker:
    def __init__(self, queue=7, mem=0.25):
        self._q, self._m = queue, mem

    def unclaimed(self, stream):
        return self._q

    def memory_ratio(self):
        return self._m


class TestFederatedSignalSource:
    def test_gather_assembles_fleet_signals_from_scraped_series(self):
        from analytics_zoo_tpu.serving.scaler import (
            FederatedSignalSource,
        )

        now = 1000.0
        st = TimeSeriesStore(clock=lambda: now)  # gather queries "now"
        for host in ("h1", "h2"):
            st.ingest(
                _hist_samples("zoo_serving_predict_seconds", [0.05])
                + [_counter_sample("zoo_serving_records_total", 0)],
                ts=now - 10.0, source={"host": host})
            st.ingest(
                _hist_samples("zoo_serving_predict_seconds",
                              [0.05, 0.2, 0.2])
                + [_counter_sample("zoo_serving_records_total", 20)],
                ts=now, source={"host": host})
        fed = FederatedSignalSource(st, _FakeBroker(), "s")
        sig = fed.gather(15.0)
        assert sig.window_count == 4
        assert sig.service_rate == pytest.approx(4.0)
        assert sig.queue_depth == 7
        assert sig.memory_ratio == pytest.approx(0.25)
        assert 0.1 < sig.predict_p99_s <= 0.5
        # no scraper attached: hosts = distinct stored sources
        assert fed.host_count() == 2

    def test_host_count_prefers_scraper_verdict(self):
        from analytics_zoo_tpu.serving.scaler import (
            FederatedSignalSource,
        )

        class Sc:
            def healthz(self):
                return {"targets": {"a": {"healthy": True},
                                    "b": {"healthy": False}}}

        fed = FederatedSignalSource(TimeSeriesStore(), _FakeBroker(),
                                    "s", scraper=Sc())
        assert fed.host_count() == 1


# ---------------------------------------------------------------------------
# supervisor heartbeat SLO
# ---------------------------------------------------------------------------


class TestSupervisorHeartbeatSlo:
    def test_stale_heartbeat_burns_and_logs_once_per_episode(
            self, tmp_path):
        from analytics_zoo_tpu.elastic.supervisor import TrainSupervisor

        sup = TrainSupervisor(
            "dir:" + str(tmp_path / "spool"),
            {"ckpt_dir": str(tmp_path / "ckpt")}, workers=1,
            lease_ms=800,
            hb_slo=SloSpec("worker_heartbeat",
                           "zoo_elastic_hb_age_seconds",
                           threshold=0.3, objective=0.5,
                           kind="ceiling", short_window=0.6,
                           long_window=1.2))
        # w0's training loop is wedged: hb hash stopped moving 5s ago
        sup.ledger.broker.hset(
            sup.ledger.hb_key("w0"),
            {"ts": time.time() - 5.0, "role": "spare"})
        for _ in range(9):
            sup._check_heartbeat_slo({"members": ["w0", "w9"]})
            time.sleep(0.2)
        hb = [d for d in sup.decision_log() if d["action"] == "hb_slo"]
        # fired, once per episode (not once per tick past the burn)
        assert len(hb) == 1
        d = hb[0]
        assert d["worker"] == "w0" and d["reason"] == "heartbeat_burn"
        assert d["short_burn"] >= 1.0 and d["long_burn"] >= 1.0
        # no live process to SIGTERM -> verdict logged, not killed
        assert d["verdict"] == "log"
        assert [s.name for s in sup._hb_engine.specs()] == \
            ["worker_heartbeat:w0"]  # w9 never heartbeat: no spec


# ---------------------------------------------------------------------------
# metrics_dump panels + ZooConfig knobs
# ---------------------------------------------------------------------------


class TestMetricsDumpPanels:
    def _dump(self):
        _tools()
        import metrics_dump

        return metrics_dump

    def _doc(self, firing=True):
        return {"scrape": [{
            "healthy": False, "interval": 0.5, "stale_after": 1.5,
            "targets": {"rep-0": {
                "url": "http://127.0.0.1:9090", "healthy": False,
                "age_seconds": 12.3, "fetches": 40, "errors": 3,
                "last_error": "TimeoutError('timed out')",
                "remote_healthy": None, "static": False}},
        }], "slo": [{
            "specs": [{"name": "predict_latency",
                       "family": "zoo_serving_predict_seconds",
                       "threshold": 0.08, "objective": 0.95,
                       "kind": "latency", "short_window": 1.5,
                       "long_window": 6.0, "burn_threshold": 1.0,
                       "labels": {}, "description": ""}],
            "alerts": [{"slo": "predict_latency", "firing": firing,
                        "short_burn": 2.9, "long_burn": 1.4,
                        "burn_threshold": 1.0, "threshold": 0.08,
                        "objective": 0.95, "since": 1000.0,
                        "ts": 1010.0}],
            "decisions": [{"ts": 1000.0, "slo": "predict_latency",
                           "state": "firing", "short_burn": 2.9,
                           "long_burn": 1.4}],
        }]}

    def test_render_scrape_panel(self):
        md, out = self._dump(), []
        md.render_scrape(self._doc(), out=out)
        text = "\n".join(out)
        assert "rep-0" in text and "TimeoutError" in text
        assert "healthy=False" in text or "healthy=no" in text

    def test_render_slo_panel_marks_firing(self):
        md, out = self._dump(), []
        md.render_slo(self._doc(firing=True), out=out)
        text = "\n".join(out)
        assert "predict_latency" in text and "*" in text
        md.render_slo(self._doc(firing=False), out=(out2 := []))
        assert "*predict_latency" not in "\n".join(out2)

    def test_prefix_filter_gates_panels(self):
        md = self._dump()
        md.render_scrape(self._doc(), prefix="zoo_slo", out=(o := []))
        assert o == []
        md.render_slo(self._doc(), prefix="zoo_scrape", out=(o2 := []))
        assert o2 == []


class TestZooConfigZoowatchKnobs:
    def test_defaults(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig

        for k in list(os.environ):
            if k.startswith(("ZOO_SCRAPE", "ZOO_SLO")):
                monkeypatch.delenv(k)
        cfg = ZooConfig()
        assert cfg.scrape_targets is None
        assert cfg.scrape_interval == 1.0
        assert cfg.slo_objective == 0.99
        assert cfg.slo_short_window < cfg.slo_long_window

    @pytest.mark.parametrize("env,val", [
        ("ZOO_SLO_OBJECTIVE", "1.5"),
        ("ZOO_SLO_OBJECTIVE", "0"),
        ("ZOO_SCRAPE_INTERVAL", "0.001"),
        ("ZOO_SLO_BURN_THRESHOLD", "-1"),
        ("ZOO_SLO_SHORT_WINDOW", "600"),  # > default long 300
    ])
    def test_bad_values_rejected_eagerly_naming_the_var(
            self, monkeypatch, env, val):
        from analytics_zoo_tpu.common.engine import ZooConfig

        monkeypatch.setenv(env, val)
        with pytest.raises(ValueError) as e:
            ZooConfig()
        assert "ZOO_S" in str(e.value)


# ---------------------------------------------------------------------------
# metrics-docs drift gate
# ---------------------------------------------------------------------------


class TestMetricsDocsDrift:
    # quoted zoo_* literals that are NOT metric families
    NOT_METRICS = {
        "zoo_current_span",  # tracing contextvar name
        "zoo_export",        # ONNX export graph name
    }

    def test_every_family_in_source_is_documented(self):
        pkg = os.path.join(REPO, "analytics_zoo_tpu")
        found = set()
        for root, _, files in os.walk(pkg):
            for f in files:
                if not f.endswith(".py"):
                    continue
                with open(os.path.join(root, f)) as fh:
                    found |= set(re.findall(
                        r"""["'](zoo_[a-z0-9_]+)["']""", fh.read()))
        # trailing-underscore literals are PREFIXES (zoo_pmem_ spool
        # files, dynamic families) — not documentable family names
        families = {f for f in found
                    if not f.endswith("_")} - self.NOT_METRICS
        assert len(families) > 50  # the scan itself works
        with open(os.path.join(REPO, "docs",
                               "observability.md")) as fh:
            docs = fh.read()
        missing = sorted(f for f in families if f not in docs)
        assert not missing, (
            "metric families referenced in code but absent from "
            f"docs/observability.md: {missing} — document them (or "
            "add to NOT_METRICS if they are not metric families)")


# ---------------------------------------------------------------------------
# acceptance bench guards (heavy e2e — quick tier + nightly)
# ---------------------------------------------------------------------------


class TestFederatedAcceptance:
    def _bench(self):
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        import bench

        return bench

    def test_federated_scaler_bench_quick_tier(self):
        """A process-mode fleet's per-replica /varz is scraped; the
        scaler runs ONLY on the federated view through a 10x load step;
        the burn alert fires at /alertz before the first hard SLO
        violation window (the ISSUE 17 acceptance)."""
        res = self._bench().federated_scaler_bench(quick=True)
        assert res["federated"] is True
        assert res["scrape_targets_final"] >= 1
        assert res["scaled_up"] and res["max_replicas_seen"] >= 2
        assert res["alert_t_s"] is not None
        assert res["alert_before_hard_violation"] is True
        assert max(res["hosts_seen"]) >= 1
        assert res["served"] == res["enqueued"]

    def test_chaos_explainability_bench_quick_tier(self, tmp_path):
        """A ChaosSchedule elastic run's per-process flight dumps merge
        into ONE timeline where every generation change, takeover and
        respawn has its cause event within clock-skew tolerance."""
        res = self._bench().chaos_explainability_bench(
            quick=True, keep_artifacts_in=str(tmp_path))
        assert res["flight_dumps_merged"] >= 3
        assert res["chaos_events_seen"] >= 1
        assert res["generation_changes"] >= 2
        assert res["skew_beyond_tolerance"] == []
        assert res["all_effects_have_causes"] is True
        assert all(e["cause"] for e in res["explained"])
        assert os.path.exists(res["merged_trace_artifact"])
