"""Registry-enforced oracle coverage for EVERY public keras layer.

The reference makes untested layers a CI failure via registry-driven
serialization specs (zoo/src/test/.../serializer/SerializerSpec.scala:32:
``expected.add`` registry + SerializerSpecHelper scanning for unregistered
modules).  The TPU analogue: this test enumerates the public surface of
``analytics_zoo_tpu.pipeline.api.keras.layers`` and fails if

  1. any public layer has no entry in ``oracle_registry.ORACLE_TESTS``, or
  2. any registry entry points at a test function that does not exist
     (so the registry cannot rot into fiction), or
  3. the registry names a layer that no longer exists (stale entry).

Adding a new layer without an oracle test therefore breaks CI — exactly
the reference's enforcement semantics.
"""

import ast
import inspect
import os

import pytest

from oracle_registry import ORACLE_TESTS

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


def _public_layer_names():
    import analytics_zoo_tpu.pipeline.api.keras.layers as L

    names = []
    for n in dir(L):
        if n.startswith("_"):
            continue
        obj = getattr(L, n)
        if inspect.ismodule(obj):
            continue
        names.append(n)
    return sorted(names)


def _test_names_in(path):
    tree = ast.parse(open(os.path.join(REPO, path)).read())
    found = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("test"):
            found.add(node.name)
    return found


def test_every_public_layer_has_an_oracle_test():
    missing = [n for n in _public_layer_names() if n not in ORACLE_TESTS]
    assert not missing, (
        f"{len(missing)} public layers lack an oracle test — add one and "
        f"register it in tests/oracle_registry.py: {missing}")


def test_registry_entries_point_at_real_tests():
    cache = {}
    broken = []
    for layer, (path, test_name) in ORACLE_TESTS.items():
        if path not in cache:
            full = os.path.join(REPO, path)
            cache[path] = _test_names_in(path) if os.path.exists(full) \
                else None
        names = cache[path]
        if names is None:
            broken.append(f"{layer}: file {path} does not exist")
        elif test_name not in names:
            broken.append(f"{layer}: {path} has no test '{test_name}'")
    assert not broken, "\n".join(broken)


def test_registry_has_no_stale_entries():
    public = set(_public_layer_names())
    stale = [n for n in ORACLE_TESTS if n not in public]
    assert not stale, f"registry names nonexistent layers: {stale}"
