"""tfpark-equivalent high-level APIs: TFEstimator (model_fn contract),
KerasModel, GANEstimator, BERT estimators, text models
(reference pyzoo/zoo/tfpark/**)."""

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Flatten
from analytics_zoo_tpu.tfpark import (
    GANEstimator,
    KerasModel,
    TFEstimator,
    TFEstimatorSpec,
)
from analytics_zoo_tpu.tfpark.text.estimator import (
    BERTClassifier,
    BERTNER,
    bert_input_fn,
)
from analytics_zoo_tpu.tfpark.text.keras import (
    IntentEntity,
    NER,
    SequenceTagger,
)


@pytest.fixture(autouse=True)
def ctx():
    return init_zoo_context(seed=0)


def _blobs(n=256, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    centers = rng.normal(size=(classes, d)) * 3
    x = centers[y] + rng.normal(size=(n, d)) * 0.3
    return x.astype(np.float32), y.astype(np.int32)


class TestTFEstimator:
    def _model_fn(self, features, labels, mode, params):
        from analytics_zoo_tpu.tfpark.text.estimator.bert_classifier import (
            sparse_ce,
        )

        h = Dense(16, activation="relu")(features)
        probs = Dense(3, activation="softmax")(h)
        if mode == "predict" or labels is None:
            return TFEstimatorSpec(mode, predictions=probs)
        return TFEstimatorSpec(mode, predictions=probs,
                               loss=sparse_ce(probs, labels))

    def test_train_evaluate_predict(self):
        x, y = _blobs()
        est = TFEstimator(self._model_fn, optimizer="adam")
        est.train(lambda: (x, y), steps=200, batch_size=32)
        metrics = est.evaluate(lambda: (x, y), ["accuracy"])
        assert metrics["accuracy"] > 0.85
        assert "loss" in metrics
        preds = est.predict(lambda: x)
        assert preds.shape == (len(x), 3)
        assert (np.argmax(preds, -1) == y).mean() > 0.85

    def test_gradient_clipping_trains(self):
        x, y = _blobs(n=64)
        est = TFEstimator(self._model_fn, optimizer="sgd")
        est.set_constant_gradient_clipping(-0.1, 0.1)
        est.train(lambda: (x, y), steps=4, batch_size=32)
        est2 = TFEstimator(self._model_fn, optimizer="sgd")
        est2.set_gradient_clipping_by_l2_norm(1.0)
        est2.train(lambda: (x, y), steps=4, batch_size=32)
        est2.clear_gradient_clipping()
        assert est2._grad_clip is None

    def test_predict_before_train_uses_fresh_params(self):
        x, y = _blobs(n=64)
        est = TFEstimator(self._model_fn, optimizer="adam")
        preds = est.predict(lambda: x)  # no prior train(): random init
        assert preds.shape == (64, 3)

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            TFEstimatorSpec("train", loss=None)
        with pytest.raises(TypeError):
            TFEstimatorSpec("train", loss=np.zeros(3))


class TestKerasModel:
    def test_fit_eval_predict_save(self, tmp_path):
        from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

        x, y = _blobs()
        net = Sequential()
        net.add(Dense(16, activation="relu", input_shape=(8,)))
        net.add(Dense(3, activation="softmax"))
        net.compile(optimizer="adam",
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        m = KerasModel(net)
        m.fit(x, y, batch_size=32, epochs=12)
        res = m.evaluate(x, y)
        assert res["accuracy"] > 0.85
        assert (m.predict_classes(x) == y).mean() > 0.85
        p = str(tmp_path / "m.zoo")
        m.save_model(p)
        m2 = KerasModel.load_model(p)
        np.testing.assert_allclose(m2.predict(x), m.predict(x), atol=1e-5)


class TestGANEstimator:
    def test_gan_learns_shifted_gaussian(self, tmp_path):
        # real data ~ N(3, 0.5); generator should move its output mean
        rng = np.random.default_rng(0)
        n = 512
        noise = rng.normal(size=(n, 4)).astype(np.float32)
        real = (3.0 + 0.5 * rng.normal(size=(n, 2))).astype(np.float32)

        def generator_fn(z):
            h = Dense(16, activation="relu")(z)
            return Dense(2)(h)

        def discriminator_fn(x):
            h = Dense(16, activation="relu")(x)
            return Dense(1)(h)

        import jax.numpy as jnp

        def g_loss(fake_logits):
            return jnp.mean(jnp.logaddexp(0.0, -fake_logits))

        def d_loss(real_logits, fake_logits):
            return jnp.mean(jnp.logaddexp(0.0, -real_logits)) + \
                jnp.mean(jnp.logaddexp(0.0, fake_logits))

        est = GANEstimator(
            generator_fn, discriminator_fn, g_loss, d_loss,
            generator_optimizer="adam", discriminator_optimizer="adam",
            model_dir=str(tmp_path))
        est.train((noise, real), steps=600, batch_size=64)
        samples = est.generate(noise[:256])
        assert samples.shape == (256, 2)
        # untrained generator outputs are centered near 0; after training the
        # distribution must have moved decisively toward the real mean of 3
        assert samples.mean() > 1.2

    def test_generate_from_checkpoint(self, tmp_path):
        rng = np.random.default_rng(1)
        noise = rng.normal(size=(64, 4)).astype(np.float32)
        real = rng.normal(size=(64, 2)).astype(np.float32)

        def generator_fn(z):
            return Dense(2)(z)

        def discriminator_fn(x):
            return Dense(1)(x)

        import jax.numpy as jnp

        est = GANEstimator(
            generator_fn, discriminator_fn,
            lambda f: jnp.mean(-f), lambda r, f: jnp.mean(f - r),
            "sgd", "sgd", model_dir=str(tmp_path))
        est.train((noise, real), steps=5, batch_size=32)
        ref = est.generate(noise)
        # fresh estimator restores from the checkpoint dir
        est2 = GANEstimator(
            generator_fn, discriminator_fn,
            lambda f: jnp.mean(-f), lambda r, f: jnp.mean(f - r),
            "sgd", "sgd", model_dir=str(tmp_path))
        np.testing.assert_allclose(est2.generate(noise), ref, atol=1e-5)
        # training after generate() must still build the discriminator
        est2.train((noise, real), steps=2, batch_size=32)
        with pytest.raises(ValueError):
            est2.train((noise[:8], real[:8]), steps=1, batch_size=32)


SEQ = 12


def _token_task(n=128, vocab=50, seq=SEQ, classes=3, seed=0):
    """Learnable: class = first token id % classes."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, vocab, size=(n, seq))
    y = (ids[:, 0] % classes).astype(np.int32)
    return ids.astype(np.int32), y


def _tiny_bert_kwargs():
    """Shared tiny-BERT config for both estimator test classes."""
    return dict(vocab=50, hidden_size=16, n_block=1, n_head=2,
                seq_len=SEQ, intermediate_size=32)


class TestBERTEstimators:
    def _tiny_kwargs(self):
        return _tiny_bert_kwargs()

    def test_bert_classifier_trains(self):
        ids, y = _token_task()
        est = BERTClassifier(num_classes=3, optimizer="adam",
                            **self._tiny_kwargs())
        input_fn = bert_input_fn({"input_ids": ids, "labels": y}, SEQ)
        est.train(input_fn, steps=150, batch_size=32)
        acc = est.evaluate(input_fn, ["accuracy"])["accuracy"]
        assert acc > 0.7

    def test_bert_ner_shapes(self):
        ids, _ = _token_task()
        tags = (ids % 4).astype(np.int32)  # per-token labels
        est = BERTNER(num_entities=4, optimizer="adam",
                      **self._tiny_kwargs())
        input_fn = bert_input_fn({"input_ids": ids, "labels": tags}, SEQ)
        est.train(input_fn, steps=5, batch_size=32)
        preds = est.predict(input_fn)
        assert preds.shape == (len(ids), SEQ, 4)

    def test_warm_start_checkpoint(self, tmp_path):
        ids, y = _token_task(n=64)
        est = BERTClassifier(num_classes=3, optimizer="adam",
                            **self._tiny_kwargs())
        input_fn = bert_input_fn({"input_ids": ids, "labels": y}, SEQ)
        est.train(input_fn, steps=3, batch_size=32)
        ckpt = str(tmp_path / "bert_init.npz")
        est.save_init_checkpoint(ckpt)
        est2 = BERTClassifier(num_classes=3, optimizer="adam",
                             init_checkpoint=ckpt, **self._tiny_kwargs())
        est2._ensure_built(est2._to_feature_set(input_fn()), "train")
        # encoder weights restored from the first estimator
        import jax

        p1 = est._train_net.params[est.bert.name]
        p2 = est2._train_net.params[est2.bert.name]
        a = jax.tree_util.tree_leaves(p1)[0]
        b = jax.tree_util.tree_leaves(p2)[0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestTextKerasModels:
    def _word_char_data(self, n=96, vocab=40, cvocab=20, seq=8, wlen=5,
                        classes=4, seed=0):
        rng = np.random.default_rng(seed)
        words = rng.integers(1, vocab, size=(n, seq)).astype(np.int32)
        chars = rng.integers(1, cvocab, size=(n, seq, wlen)).astype(np.int32)
        tags = (words % classes).astype(np.int32)
        return words, chars, tags

    def test_ner_learns_token_tags(self):
        words, chars, tags = self._word_char_data()
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        ner = NER(num_entities=4, word_vocab_size=40, char_vocab_size=20,
                  word_length=5, seq_len=8, word_emb_dim=16, char_emb_dim=8,
                  tagger_lstm_dim=16, optimizer=Adam(lr=0.01))
        ner.fit([words, chars], tags, batch_size=32, epochs=40)
        preds = ner.predict([words, chars])
        assert preds.shape == (len(words), 8, 4)
        acc = (np.argmax(preds, -1) == tags).mean()
        assert acc > 0.6

    def test_sequence_tagger_two_heads(self):
        words, chars, tags = self._word_char_data()
        pos = (words % 3).astype(np.int32)
        tagger = SequenceTagger(num_pos_labels=3, num_chunk_labels=4,
                                word_vocab_size=40, seq_len=8,
                                feature_size=16)
        tagger.fit(words, [pos, tags], batch_size=32, epochs=3)
        pos_p, chunk_p = tagger.predict(words)
        assert pos_p.shape == (len(words), 8, 3)
        assert chunk_p.shape == (len(words), 8, 4)

    def test_intent_entity_two_heads(self):
        words, chars, tags = self._word_char_data()
        intents = (words[:, 0] % 3).astype(np.int32)
        m = IntentEntity(num_intents=3, num_entities=4, word_vocab_size=40,
                         char_vocab_size=20, word_length=5, seq_len=8,
                         word_emb_dim=16, char_emb_dim=8, char_lstm_dim=8,
                         tagger_lstm_dim=16)
        m.fit([words, chars], [intents, tags], batch_size=32, epochs=3)
        intent_p, ent_p = m.predict([words, chars])
        assert intent_p.shape == (len(words), 3)
        assert ent_p.shape == (len(words), 8, 4)


class TestBERTEstimatorDepth:
    """Beyond-smoke coverage of the BERT estimator family (VERDICT r4
    weak #10): each estimator's full train -> evaluate -> predict
    configuration on a learnable task, plus the model_dir resume flow."""

    def _tiny_kwargs(self):
        return _tiny_bert_kwargs()

    def test_squad_learns_marker_spans(self):
        """Synthetic extractive QA: the answer span starts at the marker
        token 7 and ends at marker 9 — the start/end heads must find
        them."""
        from analytics_zoo_tpu.tfpark.text.estimator import BERTSquad

        rng = np.random.default_rng(3)
        n = 96
        ids = rng.integers(10, 50, size=(n, SEQ)).astype(np.int32)
        starts = rng.integers(0, SEQ - 2, size=n)
        ends = starts + rng.integers(1, 3, size=n)
        ids[np.arange(n), starts] = 7
        ids[np.arange(n), np.minimum(ends, SEQ - 1)] = 9
        labels = np.stack([starts, np.minimum(ends, SEQ - 1)],
                          axis=1).astype(np.int32)

        est = BERTSquad(optimizer="adam", **self._tiny_kwargs())
        input_fn = bert_input_fn({"input_ids": ids, "labels": labels}, SEQ)
        est.train(input_fn, steps=200, batch_size=32)
        start_p, end_p = est.predict(input_fn)
        assert start_p.shape == (n, SEQ) and end_p.shape == (n, SEQ)
        start_acc = float(np.mean(np.argmax(start_p, -1) == labels[:, 0]))
        end_acc = float(np.mean(np.argmax(end_p, -1) == labels[:, 1]))
        assert start_acc > 0.7, start_acc
        assert end_acc > 0.7, end_acc

    def test_ner_trains_and_evaluates_per_token(self):
        """NER beyond shapes: learn tags = f(token id), evaluate with the
        per-token accuracy metric through estimator.evaluate."""
        ids, _ = _token_task()
        tags = (ids % 4).astype(np.int32)
        est = BERTNER(num_entities=4, optimizer="adam",
                      **self._tiny_kwargs())
        input_fn = bert_input_fn({"input_ids": ids, "labels": tags}, SEQ)
        est.train(input_fn, steps=200, batch_size=32)
        out = est.evaluate(input_fn, ["accuracy"])
        assert out["accuracy"] > 0.8, out
        assert "loss" in out

    def test_model_dir_resumes_training(self, tmp_path):
        """The reference estimator's model_dir contract: a NEW estimator
        instance pointed at the same model_dir continues from the
        checkpoint instead of from scratch."""
        ids, y = _token_task()
        md = str(tmp_path / "bert_md")
        input_fn = bert_input_fn({"input_ids": ids, "labels": y}, SEQ)

        est = BERTClassifier(num_classes=3, optimizer="adam",
                             model_dir=md, **self._tiny_kwargs())
        est.train(input_fn, steps=150, batch_size=32)
        acc1 = est.evaluate(input_fn, ["accuracy"])["accuracy"]

        est2 = BERTClassifier(num_classes=3, optimizer="adam",
                              model_dir=md, **self._tiny_kwargs())
        est2.train(input_fn, steps=1, batch_size=32)  # resume + 1 step
        acc2 = est2.evaluate(input_fn, ["accuracy"])["accuracy"]
        # a from-scratch net after 1 step sits near chance (~1/3); the
        # resumed one must retain the trained accuracy
        assert acc2 > max(0.6, acc1 - 0.15), (acc1, acc2)
