"""Transfer-learning API: freeze / unfreeze / freeze_up_to / new_graph.

Reference surface: NetUtils.scala (freeze/unFreeze/freezeUpTo/newGraph)
as used by the dogs-vs-cats app
(/root/reference/apps/dogs-vs-cats/transfer-learning.ipynb): truncate a
pretrained net at a feature layer, freeze the backbone, train a new head.
Here frozen layers are masked out of the optimizer update inside the
jitted SPMD train step.
"""

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense


def _data(n=128, dim=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 2.0, size=(classes, dim))
    y = rng.integers(classes, size=n)
    x = (centers[y] + rng.normal(0, 0.3, (n, dim))).astype(np.float32)
    return x, y.astype(np.int32)


@pytest.fixture(autouse=True)
def _ctx():
    init_zoo_context("transfer-learning-test", seed=0)


def _leaves(tree):
    import jax
    return [np.asarray(a) for a in jax.tree_util.tree_leaves(tree)]


def test_freeze_masks_updates_sequential():
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,), name="backbone"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.build_params()
    before_backbone = _leaves(m.params["backbone"])
    before_head = _leaves(m.params["head"])

    m.freeze("backbone")
    assert m.frozen_layers == ["backbone"]
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=3)

    after_backbone = _leaves(m.params["backbone"])
    after_head = _leaves(m.params["head"])
    for a, b in zip(before_backbone, after_backbone):
        np.testing.assert_array_equal(a, b)
    assert any(not np.array_equal(a, b)
               for a, b in zip(before_head, after_head))


def test_unfreeze_restores_training():
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,), name="backbone"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.build_params()
    m.freeze("backbone")
    m.unfreeze()
    assert m.frozen_layers == []
    before = _leaves(m.params["backbone"])
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=2)
    after = _leaves(m.params["backbone"])
    assert any(not np.array_equal(a, b) for a, b in zip(before, after))


def test_freeze_adamw_weight_decay_does_not_drift():
    # updates (not just grads) are masked: decoupled weight decay must not
    # move frozen weights either.
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,), name="backbone"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.build_params()
    before = _leaves(m.params["backbone"])
    m.freeze("backbone")
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        AdamWeightDecay,
    )

    m.compile(optimizer=AdamWeightDecay(lr=1e-2, weight_decay=0.1),
              loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=2)
    for a, b in zip(before, _leaves(m.params["backbone"])):
        np.testing.assert_array_equal(a, b)


def test_freeze_up_to_sequential():
    m = Sequential()
    m.add(Dense(16, input_shape=(8,), name="f0"))
    m.add(Dense(16, name="f1"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.freeze_up_to("f1")
    assert m.frozen_layers == ["f0", "f1"]


def test_freeze_unknown_layer_raises():
    m = Sequential()
    m.add(Dense(4, input_shape=(8,)))
    with pytest.raises(ValueError, match="unknown layer"):
        m.freeze("nope")


def test_sequential_new_graph_shares_weights():
    x, _ = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,), name="feat"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.build_params()
    feats_model = m.new_graph("feat")
    assert [ly.name for ly in feats_model.layers] == ["feat"]
    out = feats_model.predict(x, batch_size=64)
    assert out.shape == (128, 16)
    # weights are shared (same arrays), not re-initialized
    for a, b in zip(_leaves(m.params["feat"]),
                    _leaves(feats_model.params["feat"])):
        np.testing.assert_array_equal(a, b)


def test_model_new_graph_and_freeze_up_to():
    x, y = _data()
    inp = Input(shape=(8,))
    h1 = Dense(16, activation="relu", name="enc1")(inp)
    h2 = Dense(8, activation="relu", name="enc2")(h1)
    out = Dense(3, activation="softmax", name="cls")(h2)
    m = Model(inp, out)
    m.build_params()

    # re-root at enc2: ancestors only, shared weights
    feat = m.new_graph("enc2")
    names = {ly.name for ly in feat.layers}
    assert "enc2" in names and "cls" not in names
    emb = feat.predict(x, batch_size=64)
    assert emb.shape == (128, 8)
    for a, b in zip(_leaves(m.params["enc1"]),
                    _leaves(feat.params["enc1"])):
        np.testing.assert_array_equal(a, b)
    # parent model is untouched by the surgery
    probs = m.predict(x, batch_size=64)
    assert probs.shape == (128, 3)

    # freeze_up_to enc2 freezes enc1+enc2 but not the classifier
    m.freeze_up_to("enc2")
    assert m.frozen_layers == ["enc1", "enc2"]
    before_enc = _leaves({k: m.params[k] for k in ("enc1", "enc2")})
    before_cls = _leaves(m.params["cls"])
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=3)
    for a, b in zip(before_enc,
                    _leaves({k: m.params[k] for k in ("enc1", "enc2")})):
        np.testing.assert_array_equal(a, b)
    assert any(not np.array_equal(a, b)
               for a, b in zip(before_cls, _leaves(m.params["cls"])))


def test_new_graph_fit_does_not_delete_parent_buffers():
    """new_graph copies weights: fine-tuning the sub-model (whose train
    step DONATES its param buffers) must leave the parent usable."""
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,), name="feat"))
    m.add(Dense(3, activation="softmax", name="head"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=2)
    sub = m.new_graph("feat")
    sub.compile(optimizer="adam", loss="mse")
    emb_target = np.zeros((len(x), 16), np.float32)
    sub.fit(x, emb_target, batch_size=32, nb_epoch=1)  # donates sub buffers
    out = m.predict(x, batch_size=64)   # parent must still be alive
    assert out.shape == (128, 3)


def test_nested_backbone_direct_fit_after_outer_fit():
    """_sync_nested hands the backbone COPIES; fitting the backbone
    directly afterwards must not delete the outer model's params."""
    x, y = _data()
    base = Sequential()
    base.add(Dense(16, activation="relu", input_shape=(8,), name="b0"))
    base.add(Dense(3, activation="softmax", name="h0"))
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    base.fit(x, y, batch_size=32, nb_epoch=1)
    feat = base.new_graph("b0")
    outer = Sequential()
    outer.add(feat)
    outer.add(Dense(3, activation="softmax", name="h1"))
    outer.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    outer.fit(x, y, batch_size=32, nb_epoch=1)
    # backbone sees post-fit weights and can itself be trained
    feat.compile(optimizer="adam", loss="mse")
    feat.fit(x, np.zeros((len(x), 16), np.float32), batch_size=32,
             nb_epoch=1)
    out = outer.predict(x, batch_size=64)
    assert out.shape == (128, 3)


def test_new_graph_then_add_keeps_pretrained_weights():
    """Extending a truncated pretrained stack with add() must keep the
    backbone weights instead of silently re-initializing them."""
    x, y = _data()
    base = Sequential()
    base.add(Dense(16, activation="relu", input_shape=(8,), name="b0"))
    base.add(Dense(3, activation="softmax", name="h0"))
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    base.fit(x, y, batch_size=32, nb_epoch=2)
    trained_b0 = _leaves(base.params["b0"])

    sub = base.new_graph("b0")
    sub.add(Dense(3, activation="softmax", name="new_head"))
    sub.build_params()
    for a, b in zip(trained_b0, _leaves(sub.params["b0"])):
        np.testing.assert_array_equal(a, b)
    assert "new_head" in sub.params
    probs = sub.predict(x, batch_size=64)
    assert probs.shape == (128, 3)


def test_freeze_up_to_no_args_raises():
    m = Sequential()
    m.add(Dense(4, input_shape=(8,)))
    with pytest.raises(ValueError, match="at least one layer"):
        m.freeze_up_to()
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    inp = Input(shape=(8,))
    gm = Model(inp, Dense(4)(inp))
    with pytest.raises(ValueError, match="at least one layer"):
        gm.freeze_up_to()


def test_save_load_with_nested_backbone(tmp_path):
    """save() strips nested device arrays (no double-pickled weights);
    load() restores both the outer tree and the nested backbone copies."""
    from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

    x, y = _data()
    base = Sequential()
    base.add(Dense(16, activation="relu", input_shape=(8,), name="b0"))
    base.add(Dense(3, activation="softmax", name="h0"))
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    base.fit(x, y, batch_size=32, nb_epoch=1)
    feat = base.new_graph("b0")
    outer = Sequential()
    outer.add(feat)
    outer.add(Dense(3, activation="softmax", name="h1"))
    outer.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    outer.fit(x, y, batch_size=32, nb_epoch=1)
    ref = outer.predict(x, batch_size=64)

    solo = tmp_path / "solo.zoo"
    nested = tmp_path / "nested.zoo"
    feat.save(str(solo))
    outer.save(str(nested))
    # the nested file holds feat's weights once (inside the outer tree),
    # so it must not be ~2x the backbone-only file heavier than the head
    # warrants; a loose structural check: stripped nets pickle no jax
    # arrays, so nested < solo + 64KB of head/config
    assert nested.stat().st_size < solo.stat().st_size + 65536
    # save() must restore live state afterwards
    assert outer.params is not None and feat.params is not None

    loaded = KerasNet.load(str(nested))
    np.testing.assert_allclose(loaded.predict(x, batch_size=64), ref,
                               rtol=1e-6, atol=1e-6)
    inner = [ly for ly in loaded.layers if isinstance(ly, KerasNet)][0]
    emb = inner.predict(x[:16], batch_size=16)   # nested copies restored
    assert emb.shape == (16, 16)


def test_transfer_learning_end_to_end():
    """The dogs-vs-cats recipe: pretrain, truncate, freeze, retrain head."""
    xs, ys = _data(n=512, classes=4, seed=1)   # "source" task
    # target task: distinguish source classes {0,1} — the dogs-vs-cats
    # setup (subset of the pretraining domain), so frozen features transfer
    keep = ys < 2
    xt, yt = xs[keep][:256], ys[keep][:256]
    base = Sequential()
    base.add(Dense(32, activation="relu", input_shape=(8,), name="b0"))
    base.add(Dense(16, activation="relu", name="b1"))
    base.add(Dense(4, activation="softmax", name="src_head"))
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    base.fit(xs, ys, batch_size=32, nb_epoch=5)

    feat = base.new_graph("b1")
    model = Sequential()
    model.add(feat)
    model.add(Dense(2, activation="softmax", name="tgt_head"))
    model.freeze(feat.name)
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    model.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    frozen_before = _leaves(base.params["b0"])
    model.fit(xt, yt, batch_size=32, nb_epoch=25)
    acc = model.evaluate(xt, yt, batch_size=64)["accuracy"]
    assert acc > 0.8
    for a, b in zip(frozen_before, _leaves(model.params[feat.name]["b0"])):
        np.testing.assert_array_equal(a, b)
