"""Fused multi-step dispatch (ZOO_STEPS_PER_DISPATCH) + compile plane.

The fused-path contract under test: K>1 changes ONLY how many
Python→device round-trips an epoch costs — the loss trajectory, final
params, checkpoints and resume behavior are bit-identical to K=1
(per-inner-step RNG folds on the global step index; partial tail chunks
fall back to the single step).  Plus the quick-tier --dispatch bench
guard and the measure_pure_step probe cache.
"""

import os

import numpy as np
import pytest

import jax


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _model():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    return m


def _init_ctx(k, **cfg_kwargs):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig

    return zoo.init_zoo_context(ZooConfig(
        seed=3, mesh_shape={"data": 8}, steps_per_dispatch=k,
        **cfg_kwargs))


def _fit(k, epochs=2, **cfg_kwargs):
    """One full training run at steps_per_dispatch=k; returns per-epoch
    losses, final params (host), and eval metrics."""
    _init_ctx(k, **cfg_kwargs)
    x, y = _data()
    m = _model()
    m.fit(x, y, batch_size=32, nb_epoch=epochs)
    params = jax.tree_util.tree_map(np.asarray, m._estimator.model.params)
    return ([h["loss"] for h in m._estimator.history], params,
            m.evaluate(x, y, batch_size=32))


def _assert_tree_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestFusedTrajectoryEquality:
    def test_k4_bitwise_equal_to_k1(self):
        """The acceptance contract: K=4 fused training reproduces the
        K=1 loss trajectory and final weights BIT-FOR-BIT (8 steps/epoch
        = 2 fused dispatches)."""
        l1, p1, e1 = _fit(1)
        l4, p4, e4 = _fit(4)
        assert l1 == l4  # bitwise: float equality, no tolerance
        _assert_tree_bitwise(p1, p4)
        assert e1 == e4

    def test_partial_tail_chunk_falls_back_to_single_step(self):
        """K=3 over 8 steps/epoch: 2 fused chunks + 2 single-step tail
        dispatches — still bit-identical."""
        l1, p1, _ = _fit(1)
        l3, p3, _ = _fit(3)
        assert l1 == l3
        _assert_tree_bitwise(p1, p3)

    def test_fused_composes_with_prefetch_plane(self):
        """ZOO_STEPS_PER_DISPATCH and the PR-4 host data plane
        (ZOO_PREFETCH_WORKERS) stack: the chunked feeder consumes the
        prefetched stream, trajectory still bit-identical."""
        l1, p1, _ = _fit(1)
        lp, pp, _ = _fit(4, prefetch_workers=2, prefetch_depth=4)
        assert l1 == lp
        _assert_tree_bitwise(p1, pp)

    def test_mid_epoch_resume_matches_k1(self, tmp_path):
        """Crash after a MID-EPOCH checkpoint (iteration 12 of 16 —
        epoch 2, batch 4) and resume with K=4: the continuation must
        replay epochs 2-4 bit-identically to an uninterrupted K=1 run."""
        from analytics_zoo_tpu.common.triggers import SeveralIteration
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        full_losses, full_params, full_eval = _fit(1, epochs=4)

        ckdir = str(tmp_path / "ck")
        x, y = _data()

        # leg 1 (K=4): 2 epochs, checkpoint every 4 optimizer steps
        _init_ctx(4)
        m = _model()
        m.set_checkpoint(ckdir)
        est = m._make_estimator()
        m._estimator = est
        est.train(FeatureSet.of(x, y), batch_size=32, nb_epoch=2,
                  checkpoint_trigger=SeveralIteration(4))
        # simulate the crash window: drop everything newer than the
        # mid-epoch-2 snapshot (iteration 12 -> next_batch=4 of epoch 2)
        removed = 0
        for f in os.listdir(ckdir):
            if not (f.startswith("ckpt-") and f.endswith(".pkl")):
                continue  # the LATEST pointer / partial tmp files
            tag = int(f.split("-")[1].split(".")[0])
            if tag > 12:
                os.remove(os.path.join(ckdir, f))
                removed += 1
        assert removed >= 1  # the epoch-2-complete snapshot existed

        # leg 2 (K=4, fresh estimator/process-equivalent): resume to 4
        _init_ctx(4)
        m2 = _model()
        m2.set_checkpoint(ckdir)
        est2 = m2._make_estimator()
        m2._estimator = est2
        est2.train(FeatureSet.of(x, y), batch_size=32, nb_epoch=4)
        assert est2.global_step == 32
        resumed_losses = [h["loss"] for h in est2.history]
        # history covers the resumed partial epoch 2 plus epochs 3-4
        assert len(resumed_losses) == 3
        assert resumed_losses == full_losses[1:]
        _assert_tree_bitwise(
            jax.tree_util.tree_map(np.asarray, m2.params), full_params)
        assert m2.evaluate(x, y, batch_size=32) == full_eval


class TestLocalEstimatorFusion:
    def test_local_k4_bitwise_equal_to_k1(self):
        """LocalEstimator.fit(steps_per_dispatch=4): same scan-fusion
        contract as the distributed estimator, on the no-mesh path.
        192 samples / batch 32 = 6 steps/epoch -> 1 fused chunk + 2
        tail singles at K=4."""
        from analytics_zoo_tpu.pipeline.estimator import LocalEstimator

        _init_ctx(1)
        x, y = _data()
        x, y = x[:192], y[:192]

        def run(k):
            from analytics_zoo_tpu.pipeline.api.keras import Sequential
            from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

            m = Sequential()
            m.add(Dense(16, activation="relu", input_shape=(8,)))
            m.add(Dense(4, activation="softmax"))
            m.build_params()
            est = LocalEstimator(
                m, "sparse_categorical_crossentropy", "adam")
            est.fit(x, y, batch_size=32, epochs=2, seed=7,
                    steps_per_dispatch=k)
            return est.history, jax.tree_util.tree_map(
                np.asarray, m.params)

        h1, p1 = run(1)
        h4, p4 = run(4)
        assert h1 == h4
        _assert_tree_bitwise(p1, p4)


class TestPureStepProbe:
    def test_repeated_probes_reuse_compiled_step(self):
        """Satellite: measure_pure_step must not re-jit per call — the
        first probe pays (and reports) compile, re-probes report 0.0
        warmup and measure steady state."""
        _init_ctx(1)
        x, y = _data()
        m = _model()
        est = m._make_estimator()
        batch = {"x": x[:32], "y": y[:32]}
        est.measure_pure_step(batch, n_steps=2)
        first_warm = est.last_probe_warmup_seconds
        assert first_warm is not None and first_warm > 0.0
        dt = est.measure_pure_step(batch, n_steps=2)
        assert est.last_probe_warmup_seconds == 0.0
        # steady-state probe is far below the compile-included warmup
        assert dt < first_warm

    def test_probe_does_not_thrash_fit_cache(self):
        """A probe with device_transform=None and a fit with a transform
        keep SEPARATE cache entries (the old single-slot cache rebuilt
        the jit on every alternation)."""
        _init_ctx(1)
        x, y = _data()
        m = _model()
        est = m._make_estimator()
        batch = {"x": x[:32], "y": y[:32]}
        est.measure_pure_step(batch, n_steps=1)
        plan_key = est._resolved_plan().cache_key()
        fn_probe = est._train_step_fns[(None, 1, plan_key)]
        dev_tf = lambda b: b  # noqa: E731
        est._train_step_for(dev_tf, 1)
        est.measure_pure_step(batch, n_steps=1)
        assert est._train_step_fns[(None, 1, plan_key)] is fn_probe
        assert len(est._train_step_fns) == 2


class TestEstimatorWarmup:
    def test_warmup_compiles_and_records_metrics(self, tmp_path):
        """warmup() AOT-compiles the K=1 and scan-K steps through the
        compile plane; a second warmup at the same shapes is served from
        the persistent cache (hit counter moves, not the miss one)."""
        from analytics_zoo_tpu.common import compile_cache
        from analytics_zoo_tpu.metrics import (
            MetricsRegistry,
            set_registry,
            snapshot,
        )

        reg = MetricsRegistry(enabled=True)
        prev = set_registry(reg)
        try:
            _init_ctx(4, compile_cache=str(tmp_path / "cc"))
            x, y = _data()
            m = _model()
            est = m._make_estimator()
            secs = est.warmup({"x": x[:32], "y": y[:32]})
            assert set(secs) == {"train_step", "train_step_scan4"}
            assert all(v > 0 for v in secs.values())

            def series(name):
                return {tuple(sorted((s.get("labels") or {}).items())): s
                        for s in snapshot(reg)["samples"]
                        if s["name"] == name}

            hist = series("zoo_compile_seconds")
            assert (("label", "train_step"),) in hist
            assert (("label", "train_step_scan4"),) in hist

            est2 = m._make_estimator()
            est2.warmup({"x": x[:32], "y": y[:32]})
            hits = series("zoo_compile_cache_hits_total")
            got = sum(s["value"] for s in hits.values())
            assert got >= 2, hits  # both re-compiles were cache hits
        finally:
            set_registry(prev)
            compile_cache.disable_persistent_cache()


class TestSeveralIterationStride:
    def test_boundary_crossing_keeps_cadence_under_k(self):
        """Under stride-K iteration observation, SeveralIteration(n)
        fires at the first boundary past each multiple of n (NOT at
        lcm(K, n)); the classic one-step walk keeps the historical
        exact-multiple behavior."""
        from analytics_zoo_tpu.common.triggers import (
            SeveralIteration,
            TrainingState,
        )

        t = SeveralIteration(100)
        st = TrainingState(epoch=1, iteration=0)
        fired = []
        for it in range(16, 801, 16):  # K=16 dispatch boundaries
            st.iteration = it
            if t(st):
                fired.append(it)
        assert fired == [112, 208, 304, 400, 512, 608, 704, 800]

        t1 = SeveralIteration(3)
        fired1 = []
        for it in range(1, 10):
            st.iteration = it
            if t1(st):
                fired1.append(it)
        assert fired1 == [3, 6, 9]
        # same-iteration re-call (epoch-boundary callback): historical
        # exact-hit rule, idempotent overwrite
        assert t1(st) and st.iteration == 9


class TestWarmupEdges:
    def test_warmup_rejects_bad_k_before_touching_cache(self):
        _init_ctx(1)
        x, y = _data()
        m = _model()
        est = m._make_estimator()
        with pytest.raises(ValueError, match="steps_per_dispatch"):
            est.warmup({"x": x[:32], "y": y[:32]}, steps_per_dispatch=0)
        assert (None, 0) not in est._train_step_fns

    def test_warmup_uses_fit_opt_placement_under_zero1(self, monkeypatch):
        """ZOO_SHARD_OPTIMIZER=1: warmup must place opt_state exactly
        like fit (_place_opt_state), or it compiles a program fit never
        dispatches."""
        monkeypatch.setenv("ZOO_SHARD_OPTIMIZER", "1")
        _init_ctx(4)
        x, y = _data()
        m = _model()
        est = m._make_estimator()
        m._estimator = est
        secs = est.warmup({"x": x[:32], "y": y[:32]})
        # ZOO_SHARD_OPTIMIZER resolves to the zero1 plan, and plan
        # programs carry per-plan compile labels (parallel/plan.py)
        assert set(secs) == {"train_step_zero1",
                             "train_step_scan4_zero1"}
        m.fit(x, y, batch_size=32, nb_epoch=1)  # reuses the warmed fns
        assert est.global_step == 8


@pytest.mark.quick
def test_dispatch_bench_quick_tier(tmp_path):
    """CI guard (satellite): the quick-sized --dispatch bench must show
    K=16 fused dispatch at least matching K=1 steps/sec on the synthetic
    dispatch-bound model, with a bitwise-equal trajectory.  The
    cold/warm compile subprocesses are skipped here (full-run only) —
    they pay a jax import each."""
    import json

    import bench

    out = str(tmp_path / "BENCH_DISPATCH_quick.json")
    doc = bench.dispatch_bench(quick=True, compile_probe=False,
                               out_path=out)
    assert doc["loss_trajectory_bitwise_equal"], doc
    k1 = doc["sweep"]["1"]["steps_per_sec"]
    k16 = doc["sweep"]["16"]["steps_per_sec"]
    assert k16 >= k1, doc
    with open(out) as f:
        artifact = json.load(f)
    assert artifact["sweep"]["16"]["speedup_vs_k1"] >= 1.0
