"""TFRecord reading + tf.train.Example codec (reference
TFDataset.from_tfrecord_file, pyzoo .../net/tf_dataset.py:456-501).

Oracle: where torch/tensorflow-free, the wire format is validated against
bytes produced independently (struct-level construction), not just
round-tripped through our own encoder.
"""

import os
import struct

import numpy as np
import pytest

from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.feature.tfrecord import (
    encode_example,
    imagenet_example_parser,
    parse_example,
    read_tfrecord_file,
    write_tfrecord_file,
)


def _hand_built_example():
    """An Example built field-by-field with struct, independent of
    encode_example: features { feature { key:"label" value { int64_list
    { value: 7 } } } feature { key:"vec" value { float_list {...} } } }"""
    def ld(tag, b):  # length-delimited field
        return bytes([tag << 3 | 2, len(b)]) + b

    int64_list = ld(3, ld(1, bytes([7])))          # Feature.int64_list
    entry1 = ld(1, b"label") + ld(2, int64_list)
    packed = struct.pack("<2f", 1.5, -2.0)
    float_list = ld(2, ld(1, packed))              # Feature.float_list
    entry2 = ld(1, b"vec") + ld(2, float_list)
    features = ld(1, entry1) + ld(1, entry2)
    return ld(1, features)                         # Example.features


class TestExampleCodec:
    def test_parse_hand_built_bytes(self):
        fm = parse_example(_hand_built_example())
        assert fm["label"] == [7]
        assert fm["vec"] == pytest.approx([1.5, -2.0])

    def test_roundtrip_all_kinds(self):
        ex = encode_example({
            "img": b"\x00\x01jpegbytes",
            "label": [3],
            "floats": np.array([0.5, 1.5], np.float32),
            "negative": [-5],
        })
        fm = parse_example(ex)
        assert fm["img"] == [b"\x00\x01jpegbytes"]
        assert fm["label"] == [3]
        assert fm["negative"] == [-5]
        assert fm["floats"] == pytest.approx([0.5, 1.5])


class TestTFRecordFile:
    def test_write_read_with_crc(self, tmp_path):
        p = str(tmp_path / "a.tfrecord")
        exs = [encode_example({"label": [i]}) for i in range(5)]
        write_tfrecord_file(p, exs)
        got = [parse_example(r)["label"][0]
               for r in read_tfrecord_file(p, verify_crc=True)]
        assert got == [0, 1, 2, 3, 4]

    def test_corrupt_payload_raises(self, tmp_path):
        p = str(tmp_path / "bad.tfrecord")
        write_tfrecord_file(p, [encode_example({"label": [1]})])
        data = bytearray(open(p, "rb").read())
        data[-6] ^= 0xFF  # flip a payload byte
        open(p, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="corrupt"):
            list(read_tfrecord_file(p, verify_crc=True))


def _imagenet_shards(tmp_path, n_shards=2, per_shard=6, size=32):
    import cv2

    rng = np.random.default_rng(0)
    paths, labels = [], []
    for s in range(n_shards):
        exs = []
        for i in range(per_shard):
            img = rng.integers(0, 255, size=(size, size, 3)).astype(np.uint8)
            ok, buf = cv2.imencode(".jpg", img[:, :, ::-1])
            assert ok
            label = int(rng.integers(1, 10))
            labels.append(label)
            exs.append(encode_example({
                "image/encoded": buf.tobytes(),
                "image/class/label": [label],
            }))
        p = str(tmp_path / f"train-{s:05d}-of-{n_shards:05d}")
        write_tfrecord_file(p, exs)
        paths.append(p)
    return paths, labels


class TestImageNetTFRecordFeatureSet:
    def test_feeds_training_batches(self, tmp_path):
        paths, labels = _imagenet_shards(tmp_path)
        fs = FeatureSet.from_tfrecord(
            paths, imagenet_example_parser(image_size=32, label_offset=-1))
        assert fs.num_samples == 12
        batches = list(fs.batches(4, shuffle=True, seed=1, epoch=0))
        assert len(batches) == 3
        for b in batches:
            assert b["x"].shape == (4, 32, 32, 3)
            assert b["x"].dtype == np.uint8
            assert b["y"].dtype == np.int32
        got = sorted(int(v) for b in batches for v in b["y"])
        assert got == sorted(x - 1 for x in labels)

    def test_sizing_does_not_decode(self, tmp_path, monkeypatch):
        # counting records must walk framing only — no cv2 decode
        paths, _ = _imagenet_shards(tmp_path)
        calls = []
        from analytics_zoo_tpu.feature import tfrecord as tfr
        orig = tfr.parse_example
        monkeypatch.setattr(tfr, "parse_example",
                            lambda b: calls.append(1) or orig(b))
        fs = FeatureSet.from_tfrecord(
            paths, imagenet_example_parser(image_size=32, label_offset=-1))
        assert fs.num_samples == 12
        assert calls == []  # sizing decoded nothing


class TestBufferedReader:
    """read_tfrecord_file walks the framing from chunked buffered reads —
    not four tiny f.read syscalls per record."""

    def _write(self, tmp_path, n=40):
        path = str(tmp_path / "buf.tfrecord")
        examples = [encode_example({"label": [i], "vec": [float(i)] * 7})
                    for i in range(n)]
        write_tfrecord_file(path, examples)
        return path, examples

    def test_tiny_chunks_cross_every_boundary(self, tmp_path):
        """chunk_size smaller than any frame forces refills inside
        headers, payloads and CRCs — records must still come out exact."""
        path, examples = self._write(tmp_path)
        for chunk in (5, 13, 64):
            got = list(read_tfrecord_file(path, verify_crc=True,
                                          chunk_size=chunk))
            assert got == examples

    def test_read_call_count_is_chunked(self, tmp_path, monkeypatch):
        path, examples = self._write(tmp_path, n=100)

        calls = []
        import builtins
        real_open = builtins.open

        def counting_open(file, *a, **kw):
            f = real_open(file, *a, **kw)
            if file == path:
                real_read = f.read
                f.read = lambda *ra: (calls.append(1), real_read(*ra))[1]
            return f

        monkeypatch.setattr(builtins, "open", counting_open)
        got = list(read_tfrecord_file(path))
        assert len(got) == 100
        # old walk: 4 reads/record = 400; buffered: whole file in a few
        assert len(calls) <= 4, len(calls)

    def test_truncated_tail_raises_under_verify(self, tmp_path):
        """verify_crc callers must not get a silently shortened stream;
        the lenient path drops the partial record, matching the old
        framing walk."""
        path, examples = self._write(tmp_path, n=10)
        with open(path, "rb") as f:
            blob = f.read()
        cut = str(tmp_path / "cut.tfrecord")
        with open(cut, "wb") as f:
            f.write(blob[:-9])  # slice off most of the last record
        with pytest.raises(ValueError, match="truncated"):
            list(read_tfrecord_file(cut, verify_crc=True))
        assert list(read_tfrecord_file(cut)) == examples[:-1]
