"""Kernel plane (ISSUE 19): ``kernel_rules`` as the FIFTH rule table on
:class:`ShardingPlan` and the hand-tuned Pallas kernels behind it —
``fused_adam`` (single HBM round trip per optimizer step),
``fused_softmax_xent`` (no (B, V) prob tensor in HBM), ``int8_matmul``
(weight-stationary int8) plus the flash wiring.

The core claims pinned here:

- every kernel's jnp fallback IS the numerical oracle: CPU runs it
  automatically, ``ZOO_KERNEL_INTERPRET=1`` forces the Pallas path in
  interpret mode and it agrees with the fallback within the recorded
  tolerance (fused_adam's fallback is BITWISE ``optax.adam``);
- an all-``"xla"`` kernel table is a true no-op — the training
  trajectory is bit-identical to a plan with no table at all;
- ``kernel_rules`` participate in the plan cache key and the
  ``+kernels`` name suffix round-trips through ``resolve_plan``;
- without ``ZOO_USE_PALLAS`` no kernel module is ever imported (the
  plane costs nothing when off); with it, the estimator swaps the
  optimizer/loss and the trajectory stays finite on CPU via fallbacks;
- eager kernels lower through the choke point under ``kernel_<name>``
  labels: a second process over a shared ``ZOO_COMPILE_CACHE``
  warm-starts every label with zero misses.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NEW_KERNEL_MODULES = (
    "analytics_zoo_tpu.ops.pallas.fused_adam",
    "analytics_zoo_tpu.ops.pallas.fused_softmax_xent",
    "analytics_zoo_tpu.ops.pallas.int8_matmul",
)


# ---------------------------------------------------------------------------
# Rule table / plan vocabulary units
# ---------------------------------------------------------------------------


class TestKernelRules:
    def test_invalid_kernel_raises_at_construction(self):
        from analytics_zoo_tpu.parallel.plan import ShardingPlan

        with pytest.raises(ValueError, match="kernel"):
            ShardingPlan(name="t", kernel_rules=((".*", "turbo"),))

    def test_cache_key_participation_and_arity(self):
        from analytics_zoo_tpu.parallel.plan import (
            data_parallel,
            with_kernels,
        )

        dp = data_parallel()
        wk = with_kernels(dp)
        assert dp.cache_key() != wk.cache_key()
        # the five rule tables + the scalar knobs: the key grew when the
        # kernel table landed — pin the arity so a silently-dropped
        # table can't alias two different programs
        assert len(wk.cache_key()) == 11
        # per-scope tables differ too
        xla_only = with_kernels(dp, rules=((".*", "xla"),))
        assert xla_only.cache_key() != wk.cache_key()

    def test_name_suffix_round_trips_through_resolve_plan(self):
        from analytics_zoo_tpu.parallel.plan import (
            DEFAULT_KERNEL_RULES,
            resolve_plan,
            with_kernels,
        )

        p = resolve_plan("dp+kernels")
        assert p.name == "dp+kernels"
        assert p.kernel_rules == with_kernels("dp").kernel_rules
        assert [k for _, k in p.kernel_rules] \
            == [k for _, k in DEFAULT_KERNEL_RULES]
        # +kernels stacks LAST — after overlap and the dtype role
        q = resolve_plan("zero1+bf16+kernels")
        assert q.name == "zero1+bf16+kernels"
        assert q.dtype_rules == ((".*", "bf16"),)
        assert len(q.kernel_rules) == len(DEFAULT_KERNEL_RULES)
        # idempotent: with_kernels on a +kernels plan keeps one suffix
        assert with_kernels(q).name == "zero1+bf16+kernels"

    def test_kernel_policy_str_and_first_match_wins(self):
        from analytics_zoo_tpu.parallel.plan import ShardingPlan

        plan = ShardingPlan(
            name="t",
            kernel_rules=((r"^attention$", "xla"), (r".*", "flash")))
        assert plan.kernel_for("attention") == "xla"
        assert plan.kernel_for("anything.else") == "flash"
        assert "attention" in plan.kernel_policy_str()
        empty = ShardingPlan(name="e")
        assert empty.kernel_policy_str() == ""
        assert empty.kernel_for("attention") is None
        assert empty.kernel_for("attention", default="xla") == "xla"

    def test_resolve_kernel_consults_active_plan(self):
        from analytics_zoo_tpu.parallel.plan import (
            ShardingPlan,
            _active_plan,
            resolve_kernel,
        )

        # no active plan: the consumer's own default applies
        assert resolve_kernel("optimizer.adam") is None
        assert resolve_kernel("attention", default="flash") == "flash"
        plan = ShardingPlan(
            name="t",
            kernel_rules=((r"^optimizer\.adam$", "fused_adam"),
                          (r"^attention$", "xla")))
        with _active_plan(plan):
            assert resolve_kernel("optimizer.adam") == "fused_adam"
            # "xla" is an explicit pick, not a fall-through
            assert resolve_kernel("attention", default="flash") == "xla"
            # unmatched scope falls back to the default
            assert resolve_kernel("loss.softmax_xent") is None

    def test_env_knobs(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig

        monkeypatch.delenv("ZOO_USE_PALLAS", raising=False)
        assert ZooConfig().use_pallas is False
        monkeypatch.setenv("ZOO_USE_PALLAS", "1")
        assert ZooConfig().use_pallas is True
        # the plan env accepts the +kernels suffix (validated eagerly)
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "zero1+bf16+kernels")
        assert ZooConfig().sharding_plan == "zero1+bf16+kernels"
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "zero1+kernelz")
        with pytest.raises(ValueError, match="ZOO_SHARDING_PLAN"):
            ZooConfig()


# ---------------------------------------------------------------------------
# Numerical parity: fallback oracle vs interpret-mode Pallas path
# ---------------------------------------------------------------------------


def _adam_steps(tx, params, grads_seq):
    state = tx.init(params)
    out = []
    for g in grads_seq:
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
        out.append(params)
    return out, state


def _grad_tree(rng, params):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            rng.normal(size=p.shape).astype(np.float32)), params)


class TestKernelParity:
    def test_fused_adam_fallback_bitwise_vs_optax(self, monkeypatch):
        from analytics_zoo_tpu.ops.pallas import fused_adam as fa

        monkeypatch.delenv("ZOO_KERNEL_INTERPRET", raising=False)
        monkeypatch.delenv("ZOO_KERNEL_FORCE_PALLAS", raising=False)
        params = {"w": jnp.zeros((32, 16), jnp.float32),
                  "b": jnp.zeros((5,), jnp.float32)}
        rng = np.random.default_rng(0)
        grads = [_grad_tree(rng, params) for _ in range(3)]
        before = dict(fa.invocation_counts)
        ours, st = _adam_steps(fa.fused_adam(1e-3), params, grads)
        ref, st_ref = _adam_steps(optax.adam(1e-3), params, grads)
        assert fa.invocation_counts["fallback"] > before["fallback"]
        for a, b in zip(jax.tree_util.tree_leaves((ours, st)),
                        jax.tree_util.tree_leaves((ref, st_ref))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fused_adam_interpret_parity_and_schedule(self, monkeypatch):
        from analytics_zoo_tpu.ops.pallas import fused_adam as fa

        monkeypatch.setenv("ZOO_KERNEL_INTERPRET", "1")
        sched = optax.exponential_decay(1e-3, 10, 0.9)
        params = {"w": jnp.ones((64,), jnp.float32) * 0.5,
                  "b": jnp.ones((3, 7), jnp.float32)}
        rng = np.random.default_rng(1)
        grads = [_grad_tree(rng, params) for _ in range(3)]
        before = dict(fa.invocation_counts)
        ours, st = _adam_steps(fa.fused_adam(sched), params, grads)
        assert fa.invocation_counts["pallas"] > before["pallas"]
        monkeypatch.delenv("ZOO_KERNEL_INTERPRET")
        ref, st_ref = _adam_steps(optax.adam(sched), params, grads)
        for a, b in zip(jax.tree_util.tree_leaves((ours, st)),
                        jax.tree_util.tree_leaves((ref, st_ref))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_softmax_xent_interpret_fwd_and_grad(self, monkeypatch):
        from analytics_zoo_tpu.ops.pallas import fused_softmax_xent as fx

        rng = np.random.default_rng(2)
        logits = jnp.asarray(
            rng.normal(size=(16, 384)).astype(np.float32) * 4.0)
        labels = jnp.asarray(
            rng.integers(0, 384, size=(16,)).astype(np.int32))

        def mean_loss(lg):
            return fx.softmax_xent(lg, labels).mean()

        monkeypatch.setenv("ZOO_KERNEL_INTERPRET", "1")
        before = dict(fx.invocation_counts)
        loss = fx.softmax_xent(logits, labels)
        grad = jax.grad(mean_loss)(logits)
        assert fx.invocation_counts["pallas"] > before["pallas"]
        monkeypatch.delenv("ZOO_KERNEL_INTERPRET")
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
        ref_grad = jax.grad(
            lambda lg: optax.softmax_cross_entropy_with_integer_labels(
                lg, labels).mean())(logits)
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   atol=2e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   atol=1e-6, rtol=1e-5)

    def test_int8_matmul_interpret_parity(self, monkeypatch):
        from analytics_zoo_tpu.ops.pallas import int8_matmul as im

        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(16, 128)).astype(np.float32))
        w = jnp.asarray(
            rng.integers(-127, 128, size=(128, 64)).astype(np.int8))
        scale = jnp.asarray(
            rng.uniform(0.005, 0.02, size=(64,)).astype(np.float32))
        monkeypatch.setenv("ZOO_KERNEL_INTERPRET", "1")
        before = dict(im.invocation_counts)
        out = im.int8_matmul(x, w, scale)
        assert im.invocation_counts["pallas"] > before["pallas"]
        monkeypatch.delenv("ZOO_KERNEL_INTERPRET")
        ref = im._reference(x, w, scale)
        denom = float(np.linalg.norm(np.asarray(ref))) or 1.0
        rel = float(
            np.linalg.norm(np.asarray(out) - np.asarray(ref))) / denom
        assert rel < 1e-4, rel
        assert out.dtype == x.dtype


# ---------------------------------------------------------------------------
# Flash wiring: kernel_rules drive attention routing, composed with bf16
# ---------------------------------------------------------------------------


class TestFlashCompose:
    def test_attention_rule_routes_flash_and_xla(self, monkeypatch):
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.ops.pallas import flash_attention as fl
        from analytics_zoo_tpu.parallel.plan import (
            _active_plan,
            data_parallel,
            with_dtype,
            with_kernels,
        )

        monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
        rng = np.random.default_rng(4)
        q, k, v = (jnp.asarray(
            rng.normal(size=(1, 2, 256, 64)).astype(np.float32) * 0.1)
            for _ in range(3))

        # bf16 dtype_rules + flash kernel_rules compose on one plan
        plan = with_kernels(with_dtype(data_parallel(), "bf16"),
                            rules=((r"^attention$", "flash"),))
        assert plan.name == "dp+bf16+kernels"
        assert plan.dtype_rules == ((".*", "bf16"),)
        before = dict(fl.invocation_counts)
        with _active_plan(plan):
            out_flash = dot_product_attention(q, k, v)
        assert fl.invocation_counts["pallas"] > before["pallas"]

        # the explicit "xla" pick pins the dense jnp path
        xla_plan = with_kernels(data_parallel(),
                                rules=((r"^attention$", "xla"),))
        before = dict(fl.invocation_counts)
        with _active_plan(xla_plan):
            out_xla = dot_product_attention(q, k, v)
        assert fl.invocation_counts["pallas"] == before["pallas"]
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(out_xla),
                                   atol=2e-3, rtol=2e-2)


# ---------------------------------------------------------------------------
# Training: all-"xla" table is bit-identical to no table at all
# ---------------------------------------------------------------------------


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _fit(mesh_size, epochs, plan=None):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(seed=3, mesh_shape={"data": mesh_size})
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=epochs, plan=plan)
    return m


def test_all_xla_table_trajectory_bit_identical():
    """kernel_rules mapping every scope to "xla" must be a pure no-op:
    the estimator sees a different plan name/cache key, but every
    consumer takes the identical XLA path — so the losses are BITWISE
    equal to a plan with no kernel table."""
    from analytics_zoo_tpu.parallel.plan import data_parallel, with_kernels

    base = _fit(2, 2)
    xla = _fit(2, 2, plan=with_kernels(data_parallel(),
                                       rules=((r".*", "xla"),)))
    l_base = [h["loss"] for h in base._estimator.history]
    l_xla = [h["loss"] for h in xla._estimator.history]
    assert l_base == l_xla, (l_base, l_xla)
    assert xla._estimator._plan_record["name"] == "dp+kernels"


# ---------------------------------------------------------------------------
# Subprocess pins: import hygiene, end-to-end knob, cache warm start
# ---------------------------------------------------------------------------


def _run_child(script, env_overrides=None, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("ZOO_USE_PALLAS", "ZOO_SHARDING_PLAN", "ZOO_COMPILE_CACHE",
              "ZOO_KERNEL_INTERPRET", "ZOO_KERNEL_FORCE_PALLAS"):
        env.pop(k, None)
    env.update(env_overrides or {})
    r = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


_FIT_CHILD = r"""
import json
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

zoo.init_zoo_context(seed=3, mesh_shape={"data": 2})
rng = np.random.default_rng(0)
x = rng.normal(size=(64, 8)).astype(np.float32)
y = rng.integers(0, 4, size=(64,)).astype(np.int32)
m = Sequential()
m.add(Dense(16, activation="relu", input_shape=(8,)))
m.add(Dense(4))
m.compile(optimizer="adam",
          loss="sparse_categorical_crossentropy_from_logits")
m.fit(x, y, batch_size=32, nb_epoch=1)

from analytics_zoo_tpu.ops.pallas import kernel_invocation_counts

out = {
    "modules": sorted(n for n in sys.modules
                      if n.startswith("analytics_zoo_tpu.ops.pallas.")),
    "plan": m._estimator._plan_record["name"],
    "losses": [float(h["loss"]) for h in m._estimator.history],
    "counts": kernel_invocation_counts(),
}
print("RESULT " + json.dumps(out))
"""


def test_no_use_pallas_imports_no_kernel_module():
    """The negative pin: a plain fit without ZOO_USE_PALLAS never
    imports a kernel module — the plane is free when off."""
    out = _run_child(_FIT_CHILD)
    for mod in _NEW_KERNEL_MODULES:
        assert mod not in out["modules"], out["modules"]
    assert not out["plan"].endswith("+kernels"), out["plan"]


def test_use_pallas_fit_swaps_consumers_and_stays_finite():
    """ZOO_USE_PALLAS=1 end to end on CPU: the resolved plan carries
    the kernel table, the estimator swap imports fused_adam and the
    loss routes through fused_softmax_xent — and every invocation takes
    the fallback (CPU has no Mosaic), so training just works."""
    out = _run_child(_FIT_CHILD, {"ZOO_USE_PALLAS": "1"})
    assert out["plan"].endswith("+kernels"), out["plan"]
    assert "analytics_zoo_tpu.ops.pallas.fused_adam" in out["modules"]
    assert "analytics_zoo_tpu.ops.pallas.fused_softmax_xent" \
        in out["modules"]
    assert all(np.isfinite(v) for v in out["losses"]), out["losses"]
    counts = out["counts"]
    assert counts["fused_adam"]["fallback"] > 0, counts
    assert counts["fused_adam"]["pallas"] == 0, counts


_KERNEL_WARM_CHILD = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.metrics import get_registry, snapshot
from analytics_zoo_tpu.ops.pallas import kernel_step
import analytics_zoo_tpu.ops.pallas.fused_adam as fa
import analytics_zoo_tpu.ops.pallas.fused_softmax_xent as fx
import analytics_zoo_tpu.ops.pallas.int8_matmul as im

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)

g = jnp.asarray(rng.normal(size=(512,)).astype(np.float32))
zeros = jnp.zeros((512,), jnp.float32)
scal = jnp.asarray([1e-3, 0.9, 0.999, 1e-8, 0.1, 0.001], jnp.float32)
kernel_step("fused_adam", fa._adam_leaf_reference)(g, zeros, zeros, scal)

logits = jnp.asarray(rng.normal(size=(32, 128)).astype(np.float32))
labels = jnp.asarray(rng.integers(0, 128, size=(32,)).astype(np.int32))
kernel_step("fused_softmax_xent", fx._reference_fwd)(logits, labels)

x = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32))
w = jnp.asarray(rng.integers(-127, 128, size=(64, 32)).astype(np.int8))
s = jnp.full((32,), 0.02, jnp.float32)
kernel_step("int8_matmul", im._reference)(x, w, s)

out = {"hits": {}, "misses": {}}
for smp in snapshot(get_registry())["samples"]:
    lab = smp["labels"].get("label", "")
    if not lab.startswith("kernel_"):
        continue
    if smp["name"] == "zoo_compile_cache_hits_total":
        out["hits"][lab] = out["hits"].get(lab, 0) + smp["value"]
    elif smp["name"] == "zoo_compile_cache_misses_total":
        out["misses"][lab] = out["misses"].get(lab, 0) + smp["value"]
print("RESULT " + json.dumps(out))
"""


def test_kernel_labels_warm_start_from_shared_cache(tmp_path):
    """Eager kernels compile through the choke point under their own
    kernel_<name> labels, so a second process over the same
    ZOO_COMPILE_CACHE warm-starts EVERY kernel label: zero misses."""
    cache = str(tmp_path / "cc")
    labels = {"kernel_fused_adam", "kernel_fused_softmax_xent",
              "kernel_int8_matmul"}
    cold = _run_child(_KERNEL_WARM_CHILD, {"ZOO_COMPILE_CACHE": cache})
    assert set(cold["misses"]) >= labels, cold
    for lab in labels:
        assert cold["misses"][lab] > 0, cold
        assert cold["hits"].get(lab, 0) == 0, cold
    warm = _run_child(_KERNEL_WARM_CHILD, {"ZOO_COMPILE_CACHE": cache})
    for lab in labels:
        assert warm["misses"].get(lab, 0) == 0, warm
        assert warm["hits"][lab] == cold["misses"][lab], (cold, warm)


# ---------------------------------------------------------------------------
# Cost model + oracle: analytic byte terms and the per-platform verdict
# ---------------------------------------------------------------------------


class TestKernelCostModel:
    def test_byte_models_match_verified_lowerings(self):
        """Pin the analytic formulas to the cross-lowered Mosaic
        measurements recorded in BENCH_KERNEL_r17.json (rel_error 0.0
        at these sizes)."""
        from analytics_zoo_tpu.analysis.costmodel import kernel_bytes

        assert kernel_bytes("fused_adam", n=4096)["kernel"] \
            == 24 * 4096 + 24
        assert kernel_bytes(
            "fused_softmax_xent", batch=128, vocab=2048)["kernel"] \
            == 4 * 128 * 2048 + 12 * 128
        assert kernel_bytes("int8_matmul", m=128, k=256, n=128)["kernel"] \
            == 4 * 128 * 256 + 256 * 128 + 4 * 128 + 4 * 128 * 128
        # and each kernel beats its XLA twin at realistic sizes
        for name, sizes in (
                ("fused_adam", {"n": 1 << 20}),
                ("fused_softmax_xent", {"batch": 256, "vocab": 32000}),
                ("int8_matmul", {"m": 128, "k": 4096, "n": 4096}),
                ("flash", {"batch": 8, "heads": 12, "seq": 2048,
                           "head_dim": 64})):
            b = kernel_bytes(name, **sizes)
            assert b["kernel"] < b["xla"], (name, b)
        with pytest.raises(ValueError, match="unknown kernel"):
            kernel_bytes("turbo", n=1)

    def test_choose_kernel_declines_on_cpu_picks_on_tpu(self):
        from analytics_zoo_tpu.analysis.costmodel import choose_kernel

        sizes = {"n": 1 << 20}
        cpu = choose_kernel("fused_adam", platform="cpu", **sizes)
        assert cpu["choice"] == "xla"
        tpu = choose_kernel("fused_adam", platform="tpu-v4", **sizes)
        assert tpu["choice"] == "fused_adam"
        # a size where the byte model predicts no win declines even
        # on TPU: flash at tiny L (the O(L²) term is negligible)
        small = choose_kernel("flash", platform="tpu-v4", batch=1,
                              heads=1, seq=0, head_dim=64)
        assert small["choice"] == "xla"

    def test_choose_plan_kernel_sweep(self):
        from analytics_zoo_tpu.analysis.costmodel import PeakTable
        from analytics_zoo_tpu.analysis.oracle import ConfigOracle

        feats = {"matmul_flops": 1e13, "bytes_accessed": 1e9}
        kwargs = dict(features=feats, activation_bytes=1 << 30)
        tpu = ConfigOracle(peaks=PeakTable(
            flops=1e12, hbm_bytes_per_s=1e11, link_bytes_per_s=1e10,
            dispatch_overhead_s=1e-5, hbm_bytes=64 << 30,
            source="tpu-test"))
        # default: no kernel options — the old candidate space exactly
        name, doc = tpu.choose_plan(1 << 30, 2 << 30, 8, **kwargs)
        assert doc.get("chosen_kernels") is None
        assert not any("+kernels" in c["config"]
                       for c in doc["candidates"])
        # swept on TPU peaks: the kernel variant wins the step factor
        name2, doc2 = tpu.choose_plan(
            1 << 30, 2 << 30, 8, kernel_options=(None, "kernels"),
            **kwargs)
        assert doc2["chosen_kernels"] == "kernels"
        assert doc2["chosen_config"].endswith("+kernels")
        # swept on CPU peaks: the factor is 1.0 and the tie breaks to
        # the plain candidate — the oracle DECLINES pallas off-TPU
        cpu = ConfigOracle(peaks=PeakTable(
            flops=1e12, hbm_bytes_per_s=1e11, link_bytes_per_s=1e10,
            dispatch_overhead_s=1e-5, hbm_bytes=64 << 30, source="cpu"))
        name3, doc3 = cpu.choose_plan(
            1 << 30, 2 << 30, 8, kernel_options=(None, "kernels"),
            **kwargs)
        assert doc3["chosen_kernels"] is None, doc3["chosen_config"]

    def test_choose_kernels_logs_to_prediction_plane(self):
        from analytics_zoo_tpu.analysis.costmodel import resolve_peaks
        from analytics_zoo_tpu.analysis.oracle import ConfigOracle

        oracle = ConfigOracle(peaks=resolve_peaks("cpu"))
        verdicts = oracle.choose_kernels(
            {"fused_adam": {"n": 1 << 20}}, platform="cpu")
        assert verdicts["fused_adam"]["choice"] == "xla"
        rows = [r for r in oracle.prediction_log()
                if r["consumer"] == "kernel_plane"]
        assert rows and rows[-1]["config"] == "kernel=fused_adam"


# ---------------------------------------------------------------------------
# Bench quick tier (the acceptance guard on bench.py --kernels)
# ---------------------------------------------------------------------------


def test_kernel_bench_quick_tier(tmp_path):
    """CI guard on the bench itself: per-kernel parity within the
    recorded tolerances, fused_adam fallback bitwise vs optax, the
    cross-lowered Mosaic custom-call bytes within 5% of the analytic
    prediction, and the CPU oracle tier declining pallas."""
    sys.path.insert(0, REPO)
    try:
        from bench import kernels_bench
    finally:
        sys.path.remove(REPO)
    doc = kernels_bench(quick=True, out_path=str(tmp_path / "b.json"))
    assert doc["value"] <= 0.05, doc["value"]
    legs = doc["kernels"]
    assert legs["fused_adam"]["parity"]["fallback_bitwise_vs_optax"] \
        is True
    for name, leg in legs.items():
        par = leg["parity"]
        for key, err in par.items():
            if key.endswith("err"):
                assert err <= par["tolerance"], (name, par)
        assert leg["bytes"]["rel_error"] <= 0.05, (name, leg["bytes"])
        assert leg["timing"]["steps_per_sec"] > 0, (name, leg["timing"])
    assert doc["cpu_xla_picks"] >= 1
    assert all(v["choice"] == "xla" for v in doc["verdicts"]["cpu"].values())
    assert doc["verdicts"]["tpu-v4"]["fused_adam"]["choice"] == "fused_adam"
