"""nnframes suite — mirrors the reference's pyzoo/test/zoo/pipeline/nnframes
tests: fit on a DataFrame, transform appends a prediction column, classifier
round-trip, image reader."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential
from analytics_zoo_tpu.pipeline.nnframes import (
    NNClassifier,
    NNClassifierModel,
    NNEstimator,
    NNImageReader,
    NNModel,
)


def _blob_df(n=256, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, d)) * 3
    y = rng.integers(0, classes, size=(n,))
    x = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)
    return pd.DataFrame({
        "features": [row for row in x],
        "label": y.astype(np.float32),
    })


class TestNNEstimator:
    def setup_method(self, _):
        init_zoo_context(seed=0)

    def test_fit_regression_and_transform(self):
        df = pd.DataFrame({
            "features": [np.array([v, v], np.float32)
                         for v in np.linspace(0, 1, 64)],
            "label": [np.array([2 * v], np.float32)
                      for v in np.linspace(0, 1, 64)],
        })
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        net = Sequential().add(Dense(1, input_shape=(2,)))
        est = (NNEstimator(net, "mse").set_optim_method(Adam(lr=0.05))
               .set_batch_size(16).set_max_epoch(40))
        model = est.fit(df)
        assert isinstance(model, NNModel)
        out = model.transform(df)
        assert "prediction" in out.columns
        pred = np.stack(out["prediction"].to_list())
        want = np.stack(df["label"].to_list())
        assert np.mean((pred - want) ** 2) < 0.05

    def test_classifier_accuracy(self):
        df = _blob_df()
        net = Sequential()
        net.add(Dense(16, input_shape=(8,), activation="relu"))
        net.add(Dense(3, activation="softmax"))
        clf = NNClassifier(net).set_batch_size(32).set_max_epoch(20)
        model = clf.fit(df)
        assert isinstance(model, NNClassifierModel)
        out = model.transform(df)
        acc = (out["prediction"].to_numpy()
               == df["label"].to_numpy()).mean()
        assert acc > 0.9

    def test_param_builders_chain(self):
        net = Sequential().add(Dense(1, input_shape=(2,)))
        est = (NNEstimator(net, "mse")
               .setFeaturesCol("f").setLabelCol("l")
               .setPredictionCol("p").setBatchSize(8).setMaxEpoch(2))
        df = pd.DataFrame({
            "f": [np.zeros(2, np.float32)] * 8,
            "l": [np.zeros(1, np.float32)] * 8,
        })
        model = est.fit(df)
        out = model.transform(df)
        assert "p" in out.columns


class TestNNImageReader:
    def test_read_images(self, tmp_path):
        from PIL import Image
        for i in range(3):
            Image.fromarray(
                np.full((10, 12, 3), i * 40, np.uint8)
            ).save(tmp_path / f"img{i}.png")
        df = NNImageReader.read_images(str(tmp_path))
        assert len(df) == 3
        assert set(["image", "origin", "height", "width",
                    "n_channels"]) <= set(df.columns)
        assert df.iloc[0]["image"].shape == (10, 12, 3)

    def test_read_images_resize(self, tmp_path):
        from PIL import Image
        Image.fromarray(np.zeros((20, 20, 3), np.uint8)).save(
            tmp_path / "a.png")
        df = NNImageReader.read_images(str(tmp_path), resize_h=8,
                                       resize_w=6)
        assert df.iloc[0]["image"].shape == (8, 6, 3)
