"""keras2 API variant tests (reference pyzoo/test/zoo keras2 suite —
run-pytests-keras2): the Keras-2-named adapters must match their Keras-1
implementations and train end-to-end."""

import numpy as np
import pytest

import jax

from analytics_zoo_tpu.pipeline.api.keras2 import Sequential, layers as k2
from analytics_zoo_tpu.pipeline.api.keras import layers as k1
from analytics_zoo_tpu.pipeline.api.keras.engine import Input


rng0 = np.random.default_rng(0)


def _run(layer, x):
    layer.ensure_built(tuple(x.shape)[1:])
    params = layer.init_params(jax.random.PRNGKey(0))
    out, _ = layer.apply(params, x)
    return np.asarray(out), params


def test_dense_matches_keras1():
    x = rng0.normal(size=(4, 6)).astype(np.float32)
    out2, p2 = _run(k2.Dense(3, activation="relu"), x)
    l1 = k1.Dense(3, activation="relu")
    l1.ensure_built((6,))
    out1, _ = l1.apply(p2, x)
    np.testing.assert_allclose(out2, np.asarray(out1), atol=1e-6)


def test_conv2d_args_translate():
    x = rng0.normal(size=(2, 8, 8, 3)).astype(np.float32)
    layer = k2.Conv2D(4, 3, strides=(2, 2), padding="same",
                      use_bias=False)
    out, params = _run(layer, x)
    assert out.shape == (2, 4, 4, 4)
    assert "bias" not in params

    with pytest.raises(ValueError, match="channels-last"):
        k2.Conv2D(4, 3, data_format="channels_first")


def test_pooling_and_dropout_names():
    x = rng0.normal(size=(2, 10, 5)).astype(np.float32)
    out, _ = _run(k2.MaxPooling1D(pool_size=2, strides=2), x)
    assert out.shape == (2, 5, 5)
    out, _ = _run(k2.AveragePooling1D(pool_size=5, strides=5), x)
    assert out.shape == (2, 2, 5)
    out, _ = _run(k2.GlobalAveragePooling1D(), x)
    assert out.shape == (2, 5)

    d = k2.Dropout(rate=0.3)
    assert d.p == pytest.approx(0.3)


def test_functional_merges():
    a = Input(shape=(4,))
    b = Input(shape=(4,))
    out = k2.maximum([a, b])
    from analytics_zoo_tpu.pipeline.api.keras2 import Model

    m = Model([a, b], out)
    xa = rng0.normal(size=(3, 4)).astype(np.float32)
    xb = rng0.normal(size=(3, 4)).astype(np.float32)
    pred = np.asarray(m.predict([xa, xb], batch_size=3))
    np.testing.assert_allclose(pred, np.maximum(xa, xb), atol=1e-6)

    out = k2.average([a, b])
    m = Model([a, b], out)
    pred = np.asarray(m.predict([xa, xb], batch_size=3))
    np.testing.assert_allclose(pred, (xa + xb) / 2, atol=1e-6)


def test_keras2_sequential_trains():
    x = rng0.normal(size=(64, 8)).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int64)

    m = Sequential()
    m.add(k2.Dense(16, activation="relu", input_shape=(8,)))
    m.add(k2.Dropout(0.1))
    m.add(k2.Dense(2))
    m.add(k2.Softmax())
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=32, nb_epoch=60)
    res = m.evaluate(x, y, batch_size=32)
    assert res["accuracy"] > 0.8, res


def test_bias_initializer_validation_rules():
    # use_bias=False makes any bias_initializer vacuously acceptable
    k2.Dense(4, use_bias=False, bias_initializer="ones")
    # Zeros-like spellings are accepted
    k2.Dense(4, bias_initializer="Zeros")

    class Zeros:
        pass

    k2.Dense(4, bias_initializer=Zeros())
    with pytest.raises(ValueError, match="zero bias"):
        k2.Conv2D(4, 3, bias_initializer="ones")
