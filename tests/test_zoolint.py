"""Static-analysis subsystem (analytics_zoo_tpu.analysis): Tier-1 AST
lint per-rule fixtures, Tier-2 HLO cost extraction exactness, the
timed_compile hook, and the package-wide CI gate.

Tier-1 fixtures live in tests/resources/zoolint_fixtures/ — one module
per rule with positive lines (marked ``POSITIVE`` in comments) and
suppressed negatives, never imported, linted statically.

Tier-2 pins the analytic features against hand counts: exact matmul
FLOPs (2·M·K·N), collective count/bytes of a 2-device psum, a planted
f64 op and host callback each raising a finding, and the acceptance
check that ``timed_compile`` of the fused train step emits
``zoo_hlo_flops`` matching the analytic hand count for the test model.

``test_package_is_clean`` is the quick-tier gate: the full linter over
``analytics_zoo_tpu/`` must report zero unsuppressed findings (the same
check ``python tools/zoolint.py analytics_zoo_tpu/`` exits 0 on).
"""

import json
import os

import numpy as np
import pytest

import jax

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(REPO, "tests", "resources", "zoolint_fixtures")


def _lint_fixture(name, rule=None):
    from analytics_zoo_tpu.analysis import lint_file

    findings = lint_file(os.path.join(FIXTURES, name))
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


def _active(findings):
    return [f for f in findings if not f.suppressed]


def _suppressed(findings):
    return [f for f in findings if f.suppressed]


def _line_of(name, marker):
    """1-based line of the first source line containing ``marker``."""
    with open(os.path.join(FIXTURES, name)) as f:
        for i, line in enumerate(f, start=1):
            if marker in line:
                return i
    raise AssertionError(f"{marker!r} not in {name}")


# ---------------------------------------------------------------------------
# Tier 1: one fixture per rule — positives found, negatives quiet,
# suppressions honored.
# ---------------------------------------------------------------------------


class TestJitSideEffectRule:
    FX = "fx_jit_side_effect.py"

    def test_positives(self):
        active = _active(_lint_fixture(self.FX, "jit-side-effect"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, 'print("tracing", x)') in lines
        assert _line_of(self.FX, "time.time()") in lines
        assert _line_of(self.FX, "np.random.rand(3)") in lines
        # transitive: helper called FROM a traced function is traced too
        assert _line_of(self.FX, '"transitively traced"') in lines
        # the plain host function must NOT fire
        assert _line_of(self.FX, "plain host function") not in lines

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "jit-side-effect"))
        assert [f.line for f in sup] == [_line_of(self.FX, '"marker"')]

    def test_severity_is_error(self):
        assert all(str(f.severity) == "error"
                   for f in _lint_fixture(self.FX, "jit-side-effect"))

    def test_nested_traced_call_attributed_to_innermost(self):
        """A side effect in a nested traced def is reported once,
        against the INNERMOST function name — deterministically (the
        traced set is identity-hashed; attribution must not depend on
        set iteration order)."""
        from analytics_zoo_tpu.analysis import lint_source

        src = ("import jax\n"
               "@jax.jit\n"
               "def outer(x):\n"
               "    def inner(y):\n"
               "        print(y)\n"
               "        return y\n"
               "    return inner(x)\n")
        found = [f for f in lint_source(src, "t.py")
                 if f.rule == "jit-side-effect"]
        assert len(found) == 1
        assert found[0].data["function"] == "inner"


class TestPrngReuseRule:
    FX = "fx_prng_reuse.py"

    def test_positive_and_negatives(self):
        active = _active(_lint_fixture(self.FX, "prng-reuse"))
        assert [f.line for f in active] == \
            [_line_of(self.FX, "POSITIVE: same key")]

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "prng-reuse"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "antithetic pair")]

    def test_nested_functions_have_separate_key_scopes(self):
        """Two sibling closures each consuming their own `key` param
        once must not read as a reuse in the enclosing function."""
        from analytics_zoo_tpu.analysis import lint_source

        src = ("import jax\n"
               "def outer():\n"
               "    def f(key):\n"
               "        return jax.random.normal(key, (2,))\n"
               "    def g(key):\n"
               "        return jax.random.uniform(key, (2,))\n"
               "    return f, g\n")
        assert not [f for f in lint_source(src, "t.py")
                    if f.rule == "prng-reuse"]

    def test_reuse_inside_nested_function_reported_once(self):
        from analytics_zoo_tpu.analysis import lint_source

        src = ("import jax\n"
               "def outer():\n"
               "    def f(key):\n"
               "        a = jax.random.normal(key, (2,))\n"
               "        b = jax.random.uniform(key, (2,))\n"
               "        return a + b\n"
               "    return f\n")
        found = [f for f in lint_source(src, "t.py")
                 if f.rule == "prng-reuse"]
        assert len(found) == 1 and found[0].line == 5


class TestHostSyncRule:
    FX = "fx_host_sync.py"

    def test_positives_only_inside_hot_path(self):
        active = _active(_lint_fixture(self.FX, "host-sync"))
        # float/asarray/block/device_get/int/.item() in the loop plus
        # the straight-line float()
        assert len(active) == 7
        cold = _line_of(self.FX, "not annotated hot-path")
        assert cold not in {f.line for f in active}

    def test_item_call_detected(self):
        active = _active(_lint_fixture(self.FX, "host-sync"))
        item_line = _line_of(self.FX, ".item()")
        hit = [f for f in active if f.line == item_line]
        assert len(hit) == 1 and hit[0].data["call"] == ".item()"

    def test_loop_context_changes_message(self):
        active = _active(_lint_fixture(self.FX, "host-sync"))
        by_line = {f.line: f for f in active}
        in_loop = by_line[_line_of(self.FX, "POSITIVE (in loop)")]
        assert in_loop.data.get("in_loop") is True
        assert "next feed" in in_loop.message
        straight = by_line[_line_of(self.FX, "not in a loop")]
        assert "in_loop" not in straight.data
        assert "next feed" not in straight.message

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "host-sync"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "epoch-boundary sync")]


class TestNonDonatedCarryRule:
    FX = "fx_nondonated_carry.py"

    def test_decorator_and_call_site_positives(self):
        active = _active(_lint_fixture(self.FX, "nondonated-carry"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, "POSITIVE (decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (call site)") in lines
        assert len(active) == 2  # donated variants stay quiet

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "nondonated-carry"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "reused across probes")]


class TestRawJitRule:
    FX = "fx_raw_jit.py"

    def test_raw_jit_positives(self):
        """Decorator, partial-decorator and call-site jits outside the
        compile plane are flagged; the timed_compile idiom and
        compile_step routing stay quiet."""
        active = _active(_lint_fixture(self.FX, "raw-jit"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, "POSITIVE (decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (partial decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (call site)") in lines
        assert len(active) == 3  # choke-point negatives stay quiet

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "raw-jit"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "deliberate bypass")]

    def test_package_train_steps_routed(self):
        """The rewired call sites the rule exists for: the estimator's
        train/eval steps and both explicit strategies now reach XLA only
        through compile_step — zero active raw-jit findings in those
        modules."""
        from analytics_zoo_tpu.analysis import lint_paths

        mods = [
            os.path.join(REPO, "analytics_zoo_tpu", p) for p in (
                "pipeline/estimator/estimator.py",
                "pipeline/estimator/local.py",
                "parallel/strategies.py",
            )
        ]
        active = [f for f in _active(lint_paths(mods))
                  if f.rule == "raw-jit"]
        assert not active, [str(f) for f in active]


class TestRawRematRule:
    FX = "fx_raw_remat.py"

    def test_raw_remat_positives(self):
        """Decorator, partial-decorator and call-site checkpoints outside
        apply_remat are flagged; the apply_remat routing stays quiet."""
        active = _active(_lint_fixture(self.FX, "raw-remat"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, "POSITIVE (decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (partial decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (call site)") in lines
        assert len(active) == 3  # apply_remat negative stays quiet

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "raw-remat"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "deliberate bypass")]

    def test_package_remat_routed(self):
        """The call sites the rule exists for: the transformer blocks and
        the pipeline stage bodies now checkpoint only through
        apply_remat/resolve_remat — zero active raw-remat findings."""
        from analytics_zoo_tpu.analysis import lint_paths

        mods = [
            os.path.join(REPO, "analytics_zoo_tpu", p) for p in (
                "pipeline/api/keras/layers/self_attention.py",
                "parallel/pipeline.py",
                "pipeline/estimator/estimator.py",
            )
        ]
        active = [f for f in _active(lint_paths(mods))
                  if f.rule == "raw-remat"]
        assert not active, [str(f) for f in active]


class TestRawPallasCallRule:
    FX = "fx_raw_pallas.py"

    def test_raw_pallas_positives(self):
        """Decorator, partial-decorator and call-site pallas_calls
        outside ops/pallas/ are flagged."""
        active = _active(_lint_fixture(self.FX, "raw-pallas-call"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, "POSITIVE (decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (partial decorator)") in lines
        assert _line_of(self.FX, "POSITIVE (call site)") in lines
        assert len(active) == 3

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "raw-pallas-call"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "deliberate bypass")]

    def test_package_kernels_routed(self):
        """The kernel plane's contract: every pl.pallas_call in the
        package lives in ops/pallas/ (where the modules carry the
        disable-file justification) and the kernel CONSUMERS carry
        none at all — zero active raw-pallas-call findings."""
        import glob

        from analytics_zoo_tpu.analysis import lint_paths

        mods = sorted(glob.glob(os.path.join(
            REPO, "analytics_zoo_tpu", "ops", "pallas", "*.py")))
        mods += [
            os.path.join(REPO, "analytics_zoo_tpu", p) for p in (
                "ops/attention.py",
                "pipeline/api/keras/objectives.py",
                "pipeline/inference/quantize.py",
                "pipeline/estimator/estimator.py",
            )
        ]
        active = [f for f in _active(lint_paths(mods))
                  if f.rule == "raw-pallas-call"]
        assert not active, [str(f) for f in active]


class TestGuardedByRule:
    FX = "fx_guarded_by.py"

    def test_unguarded_writes_caught(self):
        """The lock-discipline checker catches every write shape against
        a `# guarded-by:` attribute outside the lock."""
        active = _active(_lint_fixture(self.FX, "guarded-by"))
        lines = {f.line for f in active}
        assert _line_of(self.FX, "item assignment, no lock") in lines
        assert _line_of(self.FX, "augmented assignment, no lock") in lines
        assert _line_of(self.FX, "mutating call, no lock") in lines
        assert _line_of(self.FX, "rebinding loses") in lines
        assert _line_of(self.FX, "tuple-unpacking write") in lines
        assert len(active) == 5  # locked + undeclared writes are quiet

    def test_finding_names_attr_and_lock(self):
        f = _active(_lint_fixture(self.FX, "guarded-by"))[0]
        assert f.data["lock"] == "_lock"
        assert "_items" in f.message or "count" in f.message

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "guarded-by"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "before the worker threads start")]


class TestLockOrderRule:
    FX = "fx_lock_order.py"

    def test_abba_found_consistent_quiet(self):
        active = _active(_lint_fixture(self.FX, "lock-order"))
        assert len(active) == 1
        assert set(active[0].data["locks"]) == \
            {"AbbaPair._a_lock", "AbbaPair._b_lock"}


class TestBareExceptRule:
    FX = "fx_bare_except.py"

    def test_swallow_found_reraise_quiet(self):
        active = _active(_lint_fixture(self.FX, "bare-except"))
        assert [f.line for f in active] == \
            [_line_of(self.FX, "POSITIVE: eats SystemExit")]

    def test_suppressed_negative(self):
        sup = _suppressed(_lint_fixture(self.FX, "bare-except"))
        assert [f.line for f in sup] == \
            [_line_of(self.FX, "last-resort guard")]


class TestEngine:
    def test_file_level_suppression(self):
        from analytics_zoo_tpu.analysis import lint_source

        src = ("# zoolint: disable-file=bare-except -- fixture\n"
               "def f():\n"
               "    try:\n"
               "        pass\n"
               "    except:\n"
               "        pass\n")
        findings = lint_source(src, "t.py")
        assert all(f.suppressed for f in findings
                   if f.rule == "bare-except")

    def test_syntax_error_is_a_finding(self):
        from analytics_zoo_tpu.analysis import lint_source

        findings = lint_source("def broken(:\n", "bad.py")
        assert [f.rule for f in findings] == ["parse-error"]

    def test_render_json_shape(self):
        from analytics_zoo_tpu.analysis import lint_file, render_json

        doc = json.loads(render_json(lint_file(
            os.path.join(FIXTURES, "fx_bare_except.py"))))
        assert doc["summary"]["total"] == 1
        assert doc["summary"]["suppressed"] == 1
        assert doc["summary"]["by_rule"] == {"bare-except": 1}
        assert doc["findings"][0]["path"].endswith("fx_bare_except.py")


class TestCli:
    def test_exit_nonzero_on_findings_and_json(self, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        rc = main([FIXTURES, "--format", "json"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["summary"]["total"] > 0

    def test_exit_zero_on_clean_tree(self, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        rc = main([os.path.join(REPO, "analytics_zoo_tpu", "analysis")])
        capsys.readouterr()
        assert rc == 0

    def test_unknown_rule_is_usage_error(self, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        rc = main([FIXTURES, "--rules", "no-such-rule"])
        capsys.readouterr()
        assert rc == 2

    def test_missing_path_is_usage_error_not_clean(self, capsys):
        """A typo'd path must exit 2, not report '0 findings' — a CI
        step pointed at nothing would otherwise stay green forever."""
        from analytics_zoo_tpu.analysis.cli import main

        rc = main(["no/such/dir-anywhere"])
        capsys.readouterr()
        assert rc == 2

    def test_rule_subset(self, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        rc = main([os.path.join(FIXTURES, "fx_bare_except.py"),
                   "--rules", "guarded-by"])
        capsys.readouterr()
        assert rc == 0  # bare-except exists there, but wasn't asked for


# ---------------------------------------------------------------------------
# The CI gate (acceptance): zero unsuppressed findings over the package.
# ---------------------------------------------------------------------------


def test_package_is_clean():
    """`python tools/zoolint.py --whole-program analytics_zoo_tpu/`
    must exit 0: every real violation the per-file detectors AND the
    interprocedural pass (cross-module lock-order, guarded-by
    inference) surface is either fixed or justified with a reviewed
    suppression comment."""
    from analytics_zoo_tpu.analysis import lint_paths, render_text
    from analytics_zoo_tpu.analysis.rules_interproc import lint_program

    pkg = os.path.join(REPO, "analytics_zoo_tpu")
    findings = lint_paths([pkg]) + lint_program(pkg)
    active = _active(findings)
    assert not active, "unsuppressed zoolint findings:\n" + \
        render_text(active)


# ---------------------------------------------------------------------------
# Tier 2: analytic cost extraction + HLO findings.
# ---------------------------------------------------------------------------


class TestHloCostExtraction:
    def test_matmul_flops_exact(self):
        """FLOPs of one [8,16]x[16,4] dot: 2*8*16*4 = 1024 exactly (the
        same figure XLA's own cost analysis reports)."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((8, 16), np.float32),
            np.zeros((16, 4), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "matmul")
        assert rpt.matmul_flops == 2 * 8 * 16 * 4
        assert rpt.op_count == 1
        assert rpt.collective_count == 0
        assert not rpt.findings

    def test_batched_dot_general_flops(self):
        """Batched dims count into output, contracted dims into depth:
        [2,8,16]x[2,16,4] einsum -> 2 * (2*8*4) * 16."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        import jax.numpy as jnp
        text = jax.jit(
            lambda a, b: jnp.einsum("bij,bjk->bik", a, b)).lower(
            np.zeros((2, 8, 16), np.float32),
            np.zeros((2, 16, 4), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "bmm")
        assert rpt.matmul_flops == 2 * (2 * 8 * 4) * 16

    def test_psum_collective_count_and_bytes(self):
        """A psum over a 2-device CPU mesh is ONE all_reduce moving the
        [8]f32 result = 32 bytes."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        devices = jax.devices()[:2]
        assert len(devices) == 2, "conftest forces an 8-device CPU mesh"
        f = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i",
                     devices=devices)
        rpt = analyze_hlo_text(
            f.lower(np.zeros((2, 8), np.float32)).as_text(), "psum")
        assert rpt.collective_count == 1
        assert rpt.collectives == {"all_reduce": 1}
        assert rpt.collective_bytes == 8 * 4
        assert not rpt.findings  # all_reduce is an EXPECTED collective

    def test_planted_f64_raises_finding(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text
        from jax.experimental import enable_x64

        with enable_x64():
            text = jax.jit(lambda x: x.astype("float64") * 2.0).lower(
                np.zeros((4,), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "f64")
        assert "hlo-f64" in {f.rule for f in rpt.findings}

    def test_planted_host_callback_raises_finding(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        def cb(x):
            return np.asarray(x)

        text = jax.jit(lambda x: jax.pure_callback(
            cb, jax.ShapeDtypeStruct((4,), np.float32), x)).lower(
            np.zeros((4,), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "cb")
        rules = {f.rule for f in rpt.findings}
        assert "hlo-host-callback" in rules

    def test_unexpected_all_gather_raises_finding(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:2]), ("d",))
        g = jax.jit(shard_map(lambda x: jax.lax.all_gather(x, "d"),
                              mesh=mesh, in_specs=P("d"),
                              out_specs=P(None, "d")))
        rpt = analyze_hlo_text(
            g.lower(np.zeros((8,), np.float32)).as_text(), "ag")
        assert "hlo-all-gather" in {f.rule for f in rpt.findings}
        assert rpt.collectives.get("all_gather") == 1

    def test_large_baked_constant_raises_finding(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        big = np.arange(1024 * 300, dtype=np.float32).reshape(1024, 300)
        text = jax.jit(lambda x: x + big).lower(
            np.zeros((1024, 300), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "const")
        consts = [f for f in rpt.findings
                  if f.rule == "hlo-large-constant"]
        assert consts and consts[0].data["bytes"] == big.nbytes

    def test_splat_constant_not_flagged(self):
        """A big SPLAT constant (dense<0.0> broadcast) is cheap — only
        non-splat literals are 'baked arrays'."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        import jax.numpy as jnp
        text = jax.jit(
            lambda x: x + jnp.zeros((2048, 2048), jnp.float32)).lower(
            np.zeros((2048, 2048), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "splat")
        assert "hlo-large-constant" not in {f.rule for f in rpt.findings}

    def test_scan_counts_fused_dispatch(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = jax.jit(lambda c, xs: jax.lax.scan(
            lambda c, x: (c @ x, c.sum()), c, xs)).lower(
            np.zeros((3, 3), np.float32),
            np.zeros((5, 3, 3), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "scan")
        assert rpt.fused_dispatch_count == 1
        # dot in the (outlined) body counted ONCE: static graph features
        assert rpt.matmul_flops == 2 * 3 * 3 * 3


# ---------------------------------------------------------------------------
# Tier 2 wiring: the timed_compile hook -> metrics / flight / report.
# ---------------------------------------------------------------------------


@pytest.fixture()
def fresh_telemetry():
    from analytics_zoo_tpu.metrics import (
        FlightRecorder,
        MetricsRegistry,
        set_flight_recorder,
        set_registry,
    )

    reg, flight = MetricsRegistry(), FlightRecorder()
    prev_reg = set_registry(reg)
    prev_flight = set_flight_recorder(flight)
    yield reg, flight
    set_registry(prev_reg)
    set_flight_recorder(prev_flight)


def _gauge_value(reg, name, label):
    for fam in reg.collect():
        if fam.name == name:
            for labels, child in fam.samples():
                if labels.get("label") == label:
                    return child.get()
    raise AssertionError(f"{name}{{label={label}}} not found")


class TestTimedCompileHook:
    def test_emits_metrics_flight_and_report(self, fresh_telemetry,
                                             tmp_path, monkeypatch):
        """timed_compile of a known matmul emits zoo_hlo_flops matching
        the 2*M*K*N hand count, records the hlo_lint flight event, and
        writes the per-compile JSON report."""
        from analytics_zoo_tpu.common.compile_cache import timed_compile

        reg, flight = fresh_telemetry
        monkeypatch.setenv("ZOO_HLO_REPORT_DIR", str(tmp_path))
        lowered = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((8, 16), np.float32),
            np.zeros((16, 4), np.float32))
        timed_compile(lowered, "hlo_gate_test")

        assert _gauge_value(reg, "zoo_hlo_flops",
                            "hlo_gate_test") == 2 * 8 * 16 * 4
        assert _gauge_value(reg, "zoo_hlo_collective_bytes",
                            "hlo_gate_test") == 0
        assert _gauge_value(reg, "zoo_hlo_findings", "hlo_gate_test") == 0

        # the flight ring answers "what was compiled" after a crash
        evs = flight.events("hlo_lint")
        assert len(evs) == 1
        assert evs[0]["label"] == "hlo_gate_test"
        assert evs[0]["matmul_flops"] == 2 * 8 * 16 * 4
        assert evs[0]["findings"] == []

        # the JSON report (schema zoo-hlo-report/2: v1 payload plus
        # compile/config context — compile_seconds is stamped by the
        # timed_compile hook, the rest when the caller provides them)
        reports = [f for f in os.listdir(tmp_path)
                   if f.startswith("hlo-hlo_gate_test")]
        assert len(reports) == 1
        with open(tmp_path / reports[0]) as f:
            doc = json.load(f)
        assert doc["schema"] == "zoo-hlo-report/2"
        assert doc["compile_seconds"] is None or \
            doc["compile_seconds"] >= 0
        assert doc["features"]["matmul_flops"] == 2 * 8 * 16 * 4
        assert doc["findings"] == []

    def test_disabled_by_env(self, fresh_telemetry, monkeypatch):
        from analytics_zoo_tpu.common.compile_cache import timed_compile

        reg, flight = fresh_telemetry
        monkeypatch.setenv("ZOO_HLO_LINT", "0")
        lowered = jax.jit(lambda a: a + 1).lower(
            np.zeros((4,), np.float32))
        timed_compile(lowered, "hlo_disabled")
        assert not flight.events("hlo_lint")
        assert not any(fam.name.startswith("zoo_hlo")
                       for fam in reg.collect())

    def test_varz_surface(self, fresh_telemetry):
        """The zoo_hlo_* family rides the standard snapshot path, so
        /varz and /metrics expose it without extra wiring."""
        from analytics_zoo_tpu.analysis.hlo import lint_lowered
        from analytics_zoo_tpu.metrics import prometheus_text, snapshot

        reg, _ = fresh_telemetry
        lowered = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((2, 3), np.float32), np.zeros((3, 2), np.float32))
        lint_lowered(lowered, "varz_probe")
        names = {s["name"] for s in snapshot(reg)["samples"]}
        assert "zoo_hlo_flops" in names
        assert 'zoo_hlo_flops{label="varz_probe"}' in prometheus_text(reg)


class TestFusedTrainStepAcceptance:
    @pytest.fixture(autouse=True)
    def _reset_compile_cache(self):
        from analytics_zoo_tpu.common import compile_cache

        yield
        # the warmup below enables the persistent cache at a tmp dir:
        # turn it back off so later tests don't compile into a deleted
        # directory
        compile_cache.disable_persistent_cache()

    def test_fused_train_step_flops_match_hand_count(
            self, fresh_telemetry, tmp_path, monkeypatch):
        """Acceptance: timed_compile of the FUSED train step (scan-K)
        emits zoo_hlo_flops/zoo_hlo_collective_bytes whose matmul-FLOPs
        value matches the analytic hand count for the test model.

        Model: one Dense(8 -> 4), no bias-matmul, MSE, batch 32.
        Matmuls per step: forward x@W = 2*B*I*O, grad dW = x^T@dy =
        2*I*O*B (dx is pruned — x is not differentiated).  Hand count =
        4*B*I*O = 4096.  The scan-K body is the SAME one_step closure,
        outlined once, so the fused program's static matmul FLOPs equal
        the K=1 program's."""
        import analytics_zoo_tpu as az
        from analytics_zoo_tpu.common.engine import ZooConfig
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        reg, flight = fresh_telemetry
        monkeypatch.setenv("ZOO_COMPILE_CACHE", str(tmp_path / "cc"))
        az.init_zoo_context(ZooConfig(seed=3, mesh_shape={"data": 8},
                                      steps_per_dispatch=2))
        m = Sequential()
        m.add(Dense(4, input_shape=(8,)))
        m.compile(optimizer="sgd", loss="mse")
        est = m._make_estimator()
        batch = {
            "x": np.random.default_rng(0).normal(
                size=(32, 8)).astype(np.float32),
            "y": np.zeros((32, 4), np.float32),
        }
        est.warmup(batch, steps_per_dispatch=2)

        hand_count = 4 * 32 * 8 * 4  # fwd 2BIO + dW 2BIO
        assert _gauge_value(reg, "zoo_hlo_flops",
                            "train_step") == hand_count
        assert _gauge_value(reg, "zoo_hlo_flops",
                            "train_step_scan2") == hand_count
        # GSPMD inserts the gradient all-reduce AFTER lowering, so the
        # pre-partitioning module text carries no explicit collectives
        assert _gauge_value(reg, "zoo_hlo_collective_bytes",
                            "train_step_scan2") == 0
        # the fused program is one lax.scan = one while loop
        assert _gauge_value(reg, "zoo_hlo_fused_dispatches",
                            "train_step_scan2") == 1
        assert _gauge_value(reg, "zoo_hlo_fused_dispatches",
                            "train_step") == 0
        # flight carries one hlo_lint verdict per compiled program
        labels = [e["label"] for e in flight.events("hlo_lint")]
        assert "train_step" in labels and "train_step_scan2" in labels
