"""CheckedUnpickler (reference CheckedObjectInputStream parity): model and
checkpoint files are untrusted input; only whitelisted classes
deserialize."""

import pickle

import numpy as np
import pytest

import jax


class TestCheckedUnpickler:
    def test_malicious_reduce_refused(self, tmp_path):
        from analytics_zoo_tpu.common.safe_pickle import safe_load

        class Evil:
            def __reduce__(self):
                import os

                return (os.system, ("echo pwned",))

        p = tmp_path / "evil.pkl"
        p.write_bytes(pickle.dumps(Evil()))
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            with open(p, "rb") as f:
                safe_load(f)

    def test_builtin_eval_refused(self):
        from analytics_zoo_tpu.common.safe_pickle import safe_loads

        payload = b"cbuiltins\neval\n(V1+1\ntR."
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            safe_loads(payload)

    def test_plain_pytrees_load(self):
        from analytics_zoo_tpu.common.safe_pickle import safe_loads

        obj = {"a": np.arange(4), "b": [1.5, {"c": (2, 3)}],
               "s": {1, 2}, "od": __import__("collections").OrderedDict(
                   x=1)}
        out = safe_loads(pickle.dumps(obj))
        np.testing.assert_array_equal(out["a"], obj["a"])
        assert out["s"] == {1, 2}

    def test_model_load_is_checked(self, zoo_ctx, tmp_path):
        """KerasNet.load goes through the checked loader: a tampered model
        file with a malicious payload is refused, a real one loads."""
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

        m = Sequential()
        m.add(Dense(2, input_shape=(3,)))
        m.build_params(jax.random.PRNGKey(0))
        good = tmp_path / "model.zoo"
        m.save(str(good))
        loaded = KerasNet.load(str(good))
        x = np.zeros((2, 3), np.float32)
        np.testing.assert_allclose(
            np.asarray(loaded.predict(x)), np.asarray(m.predict(x)),
            atol=1e-6)

        class Evil:
            def __reduce__(self):
                import os

                return (os.system, ("echo pwned",))

        bad = tmp_path / "tampered.zoo"
        bad.write_bytes(pickle.dumps({"net": Evil(), "weights": None}))
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            KerasNet.load(str(bad))

    def test_checkpoint_load_is_checked(self, zoo_ctx, tmp_path):
        from analytics_zoo_tpu.pipeline.estimator.estimator import (
            _Checkpointer,
        )

        class Evil:
            def __reduce__(self):
                return (print, ("pwned",))

        ck = _Checkpointer(str(tmp_path))
        (tmp_path / "ckpt-000099.pkl").write_bytes(pickle.dumps(Evil()))
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            ck.latest()


class TestNoRootBypass:
    """Review finding: a broad numpy/jax module-root allowance is
    bypassable via exec-equivalent library callables; the allowlist must
    be exact."""

    def test_numpy_runstring_gadget_refused(self):
        import io

        from analytics_zoo_tpu.common.safe_pickle import safe_loads

        # opcode-level global reference to numpy's exec wrapper
        payload = (b"cnumpy.testing._private.utils\nrunstring\n"
                   b"(Vopen('/tmp/pwned_probe','w')\n}tR.")
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            safe_loads(payload)

    def test_arbitrary_numpy_function_refused(self):
        from analytics_zoo_tpu.common.safe_pickle import safe_loads

        payload = b"cnumpy\nload\n(V/etc/passwd\ntR."
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            safe_loads(payload)

    def test_optax_state_and_jax_treedef_still_load(self, zoo_ctx):
        import optax

        from analytics_zoo_tpu.common.safe_pickle import safe_loads

        params = {"w": np.ones((2, 2), np.float32)}
        opt_state = optax.chain(optax.clip_by_global_norm(1.0),
                                optax.adam(1e-3)).init(params)
        host = jax.tree_util.tree_map(np.asarray, opt_state)
        _, treedef = jax.tree_util.tree_flatten(params)
        blob = pickle.dumps({"opt": host, "treedef": treedef,
                             "step": np.int64(7)})
        out = safe_loads(blob)
        assert int(out["step"]) == 7
        assert out["treedef"] == treedef
