"""Per-model serving specs — the multi-tenant config surface.

``ZOO_SERVING_MODELS`` declares the models one router serves, each
with its own SLO (and optionally the offered rate the oracle sizes the
fleet for)::

    ZOO_SERVING_MODELS="resnet=250@120,bert=500@30"

i.e. comma-separated ``name=slo_p99_ms[@offered_rate]`` entries.  Each
model gets its OWN input stream on the shared broker
(:func:`~analytics_zoo_tpu.serving.client.model_stream`), its own
lease/pad-bucket/batch-budget config, and its own
``zoo_fleet_*{model=}`` telemetry — the router
(:mod:`analytics_zoo_tpu.serving.router`) runs one fleet per spec.

Pure stdlib on purpose: :class:`~analytics_zoo_tpu.common.engine
.ZooConfig` validates the string EAGERLY at construction (lazy import
from ``__post_init__`` — the ``parallel.plan`` precedent), and client
processes route by model without pulling in jax.  Every parse error
names the source (the env var by default) — the eager-validation
contract.
"""

from __future__ import annotations

import dataclasses

__all__ = ["ModelSpec", "parse_model_specs", "format_model_specs"]

_DEF_SOURCE = "ZOO_SERVING_MODELS"


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One routed model: its name, p99 SLO, and (optional) the offered
    request rate the oracle's replica math sizes for (0.0 = unknown —
    the scaler's reactive policy owns sizing alone)."""

    name: str
    slo_p99_ms: float
    offered_rate: float = 0.0

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)


def _fail(source: str, raw: str, why: str) -> None:
    raise ValueError(
        f"{source} must be comma-separated "
        f"name=slo_p99_ms[@offered_rate] entries "
        f"(e.g. \"resnet=250@120,bert=500\"); got {raw!r}: {why}")


def parse_model_specs(raw: str, source: str = _DEF_SOURCE,
                      ) -> list[ModelSpec]:
    """Parse a ``ZOO_SERVING_MODELS``-shaped string into specs.

    Empty/None input parses to ``[]`` (single-tenant serving — the
    router is not in play).  Malformed entries raise ``ValueError``
    naming ``source`` so a bad env var fails at ZooConfig construction,
    not at the first routed request."""
    if raw is None or not str(raw).strip():
        return []
    raw = str(raw)
    specs: list[ModelSpec] = []
    seen: set[str] = set()
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not name:
            _fail(source, raw, f"entry {entry!r} lacks name=slo")
        if any(c in name for c in " \t:/"):
            # the name becomes a stream key + a metric label value
            _fail(source, raw,
                  f"model name {name!r} may not contain spaces, ':' "
                  f"or '/'")
        if name in seen:
            _fail(source, raw, f"duplicate model {name!r}")
        seen.add(name)
        slo_part, _, rate_part = rest.partition("@")
        try:
            slo = float(slo_part)
        except (TypeError, ValueError):
            _fail(source, raw,
                  f"slo_p99_ms of {name!r} must be a number, got "
                  f"{slo_part!r}")
        if slo <= 0:
            _fail(source, raw,
                  f"slo_p99_ms of {name!r} must be > 0, got {slo}")
        rate = 0.0
        if rate_part.strip():
            try:
                rate = float(rate_part)
            except (TypeError, ValueError):
                _fail(source, raw,
                      f"offered_rate of {name!r} must be a number, got "
                      f"{rate_part!r}")
            if rate < 0:
                _fail(source, raw,
                      f"offered_rate of {name!r} must be >= 0, got "
                      f"{rate}")
        specs.append(ModelSpec(name=name, slo_p99_ms=slo,
                               offered_rate=rate))
    if not specs:
        _fail(source, raw, "no entries")
    return specs


def format_model_specs(specs) -> str:
    """Inverse of :func:`parse_model_specs` — the string a subprocess
    replica/controller can be handed through the env."""
    parts = []
    for s in specs:
        part = f"{s.name}={s.slo_p99_ms:g}"
        if s.offered_rate:
            part += f"@{s.offered_rate:g}"
        parts.append(part)
    return ",".join(parts)
