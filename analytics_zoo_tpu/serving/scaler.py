"""SLO-aware autoscaling policy for the serving fleet.

:class:`SloScaler` is the PURE decision function the
:class:`~analytics_zoo_tpu.serving.fleet.FleetController` ticks: it
consumes one rolling window of live fleet signals (the zootune
``Histogram.delta_since`` pattern — react to *recent* behavior, not a
lifetime blur) and answers "how many replicas should be serving".
Keeping it side-effect free makes the policy unit-testable with
fabricated windows — the controller owns threads, replicas and metrics.

The latency estimate is queueing-theory shaped rather than a bare
predict percentile: a saturated fleet shows its pain in the BACKLOG
long before predict itself slows down (predict time is per-batch and
flat under load), so the scaler estimates the tail *sojourn* time a
newly-arrived request faces as

    est_p99 = predict_p99 + unclaimed_backlog / service_rate

(Little's law for the wait, plus the service tail).  Scale-up follows
the HPA-style proportional rule ``ceil(replicas * est_p99 / slo)`` after
``up_windows`` consecutive violations — a 4x overload jumps straight
toward 4x capacity instead of creeping one replica per window — while
scale-down steps ONE replica at a time after ``down_windows``
consecutive slack windows (asymmetric on purpose: under-provisioning
burns the SLO, over-provisioning only burns idle replicas).  Broker
memory pressure is an immediate violation regardless of latency: by the
time ``memory_ratio`` reaches the server's trim threshold the fleet is
DROPPING records.

Federation tier (ISSUE 17 — the ROADMAP's planet-scale item (a)): in a
multi-host fleet the controller's local registry only sees replicas it
spawned in-process; :class:`FederatedSignalSource` builds the SAME
``FleetSignals`` window from a :class:`~analytics_zoo_tpu.metrics.
timeseries.TimeSeriesStore` that a :class:`~analytics_zoo_tpu.metrics.
scrape.VarzScraper` fills from every replica's /telemetryz — so the
policy is unchanged while the signals become cluster-wide.  The pure
policy gains a second output: :meth:`SloScaler.decide_fleet` converts
the replica target into a host target via replicas-per-host packing.
"""

from __future__ import annotations

import dataclasses
import math

__all__ = ["FleetSignals", "SloScaler", "FederatedSignalSource",
           "DEFAULT_SLO_P99_MS"]

# Default p99 SLO target (ms): generous enough that a single warm
# replica meets it on the bench synthetics, tight enough that a load
# step violates it within a couple of windows.
DEFAULT_SLO_P99_MS = 500.0


@dataclasses.dataclass
class FleetSignals:
    """One scaler window of fleet telemetry.

    ``predict_p99_s``/``window_count`` come from the registry's
    ``zoo_serving_predict_seconds`` rolling-window delta,
    ``service_rate`` from the ``zoo_serving_records_total`` delta over
    the window, ``queue_depth`` from ``Broker.unclaimed`` (claimed
    in-flight work is capacity in use, not demand), ``memory_ratio``
    from the broker."""

    predict_p99_s: float = 0.0
    window_count: int = 0
    service_rate: float = 0.0
    queue_depth: int = 0
    memory_ratio: float = 0.0


class SloScaler:
    """Sustained-violation / sustained-slack replica-count policy."""

    def __init__(self, slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                 min_replicas: int = 1, max_replicas: int = 4,
                 up_windows: int = 2, down_windows: int = 6,
                 slack_ratio: float = 0.5, memory_high: float = 0.5,
                 prior_target: int | None = None):
        if slo_p99_ms <= 0:
            raise ValueError(f"slo_p99_ms must be > 0, got {slo_p99_ms}")
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        self.slo_p99_ms = float(slo_p99_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_windows = max(1, int(up_windows))
        self.down_windows = max(1, int(down_windows))
        self.slack_ratio = float(slack_ratio)
        self.memory_high = float(memory_high)
        # oracle-seeded prior (ISSUE 20): where a FRESH fleet should
        # START.  None = the old reactive behavior (min_replicas, then
        # up_windows of violations before the first scale-up).  The
        # prior is consumed by the first decide() on an empty window —
        # after real telemetry arrives the reactive policy owns the
        # target again (the prior never caps or floors later decisions).
        if prior_target is not None:
            prior_target = min(self.max_replicas,
                               max(self.min_replicas, int(prior_target)))
        self.prior_target = prior_target
        self._prior_pending = prior_target is not None
        self._up_streak = 0
        self._down_streak = 0

    # ------------------------------------------------------------------
    def initial_target(self) -> int:
        """The replica count a fresh controller should SPAWN at: the
        oracle prior when one was seeded, ``min_replicas`` otherwise."""
        return self.prior_target if self.prior_target is not None \
            else self.min_replicas

    # ------------------------------------------------------------------
    def estimate_p99_s(self, sig: FleetSignals) -> float:
        """Estimated tail sojourn time for a request arriving NOW.

        ``inf`` when a backlog exists but nothing was served all window
        (a stalled/compiling fleet — the wait is unbounded as far as
        this window can tell); ``0.0`` on a fully idle window."""
        if sig.queue_depth > 0 and sig.service_rate <= 0:
            return math.inf
        wait = (sig.queue_depth / sig.service_rate
                if sig.service_rate > 0 else 0.0)
        return sig.predict_p99_s + wait

    # ------------------------------------------------------------------
    def decide(self, replicas: int, sig: FleetSignals) -> tuple[int, str]:
        """(target_replicas, reason) for this window; target ==
        ``replicas`` means hold (reason explains which streak is
        building, empty when fully steady)."""
        slo_s = self.slo_p99_ms / 1e3
        if self._prior_pending:
            # cold start: an empty window says NOTHING (no requests
            # have arrived), so without a prior the fleet would sit at
            # min_replicas for up_windows after the first load lands.
            # Jump straight to the oracle's target; real telemetry
            # takes over from the next window.
            self._prior_pending = False
            if sig.window_count == 0 and sig.queue_depth == 0 \
                    and replicas < self.prior_target:
                return self.prior_target, "oracle_prior"
        est = self.estimate_p99_s(sig)
        pressure = sig.memory_ratio >= self.memory_high
        violated = pressure or est > slo_s
        slack = not violated and est < self.slack_ratio * slo_s

        if violated:
            self._up_streak += 1
            self._down_streak = 0
            if self._up_streak >= self.up_windows \
                    and replicas < self.max_replicas:
                self._up_streak = 0
                if pressure:
                    # records are about to be trimmed: jump to max
                    return self.max_replicas, "broker_pressure"
                if math.isinf(est):
                    return min(replicas + 1, self.max_replicas), \
                        "stalled_backlog"
                # HPA-style proportional step toward the violating load
                target = min(self.max_replicas,
                             max(replicas + 1,
                                 math.ceil(replicas * est / slo_s)))
                return target, "slo_violation"
            return replicas, "violation_streak"
        if slack:
            self._down_streak += 1
            self._up_streak = 0
            if self._down_streak >= self.down_windows \
                    and replicas > self.min_replicas:
                self._down_streak = 0
                return replicas - 1, "sustained_slack"
            return replicas, "slack_streak"
        # in the comfort band: decay both streaks
        self._up_streak = 0
        self._down_streak = 0
        return replicas, ""

    # ------------------------------------------------------------------
    def decide_fleet(self, replicas: int, hosts: int, sig: FleetSignals,
                     replicas_per_host: int | None = None,
                     max_hosts: int | None = None,
                     ) -> tuple[int, int, str]:
        """``(target_replicas, target_hosts, reason)`` — the federated
        two-level decision.  Replica policy is :meth:`decide` verbatim;
        the host target is the packing consequence: enough hosts to
        hold the replica target at ``replicas_per_host`` (defaulting to
        the CURRENT observed packing ``ceil(replicas / hosts)``), never
        below 1, capped at ``max_hosts`` when given.  Still pure — the
        controller (or an external provisioner reading /varz) owns
        actually adding hosts."""
        target, reason = self.decide(replicas, sig)
        hosts = max(1, int(hosts))
        rph = (int(replicas_per_host) if replicas_per_host
               else max(1, math.ceil(max(1, replicas) / hosts)))
        target_hosts = max(1, math.ceil(target / rph))
        if max_hosts is not None:
            target_hosts = min(target_hosts, int(max_hosts))
        return target, target_hosts, reason


class FederatedSignalSource:
    """One scaler window assembled from SCRAPED per-host series.

    Reads the :class:`TimeSeriesStore` a :class:`VarzScraper` feeds
    (per-replica ``zoo_serving_predict_seconds`` /
    ``zoo_serving_records_total`` series, labeled by target) and the
    broker's queue state, producing the same :class:`FleetSignals` the
    local-registry path builds — the controller swaps sources, the
    policy never knows.  ``host_count()`` is the federation's second
    dimension: distinct FRESH targets currently contributing series
    (the scraper's staleness verdict keeps dead hosts out)."""

    def __init__(self, store, broker, stream: str,
                 scraper=None,
                 predict_family: str = "zoo_serving_predict_seconds",
                 records_family: str = "zoo_serving_records_total"):
        self.store = store
        self.broker = broker
        self.stream = stream
        self.scraper = scraper
        self.predict_family = predict_family
        self.records_family = records_family

    def gather(self, window_s: float) -> FleetSignals:
        """Fleet-wide window: p99 over the cross-host bucket merge,
        service rate as the sum of per-host counter rates, queue depth
        and memory ratio from the broker (shared state — already
        fleet-wide)."""
        summ = self.store.window_summary(self.predict_family, window_s)
        rate = self.store.rate(self.records_family, window_s)
        return FleetSignals(
            predict_p99_s=summ["p99"],
            window_count=summ["count"],
            service_rate=rate,
            queue_depth=self.broker.unclaimed(self.stream),
            memory_ratio=self.broker.memory_ratio(),
        )

    def host_count(self) -> int:
        """Live targets per the scraper's merged health verdict; falls
        back to counting distinct stored predict-series sources when no
        scraper is attached."""
        if self.scraper is not None:
            hz = self.scraper.healthz()
            return sum(1 for t in hz["targets"].values() if t["healthy"])
        return len(self.store.label_sets(self.predict_family))
