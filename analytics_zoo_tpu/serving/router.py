"""Multi-tenant model routing over the shared broker (ISSUE 20).

One :class:`ModelRouter` serves N models from ONE broker: each
:class:`~analytics_zoo_tpu.serving.modelspec.ModelSpec` gets its own
input stream (:func:`~analytics_zoo_tpu.serving.client.model_stream`),
its own oracle-picked serving config
(:meth:`~analytics_zoo_tpu.analysis.oracle.ConfigOracle.choose_serving`
— replica count, pad-bucket set, batch budget, int8/kernel policy),
its own prior-seeded
:class:`~analytics_zoo_tpu.serving.scaler.SloScaler`, and its own
:class:`~analytics_zoo_tpu.serving.fleet.FleetController` — a
heterogeneous replica set in which every replica still speaks nothing
but the broker's exactly-once claim protocol, so per-record leases,
takeover on death, and the serve-log audit all hold per model.

With ``admission=True`` every model stream additionally gets an
:class:`~analytics_zoo_tpu.serving.admission.AdmissionController`
(front-door shedding) and its fleet runs ``trim=False`` — accepted
work is never dropped.

Router state lands the standard three ways: the ``zoo_router_*`` /
``zoo_fleet_model_*`` metric families (per-model replica count,
backlog, estimated p99), ``router`` flight events on control actions,
and a bounded decision log in the ``router`` section of ``/varz``
(rendered by ``tools/metrics_dump.py``).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..metrics import RouterMetrics, get_flight_recorder
from .admission import AdmissionController
from .broker import connect_broker
from .client import model_stream
from .fleet import FleetController
from .modelspec import ModelSpec, parse_model_specs
from .scaler import FleetSignals, SloScaler
from .server import ClusterServingHelper

__all__ = ["ModelRouter", "varz_doc"]

# ---------------------------------------------------------------------------
# Live-router registry for /varz (metrics/http.py consults sys.modules
# only — a scrape-only process never imports this module).
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[ModelRouter]" = (  # guarded-by: _active_lock
    weakref.WeakSet())


def varz_doc() -> dict:
    """The ``router`` section of ``/varz``: every live router's
    per-model state plus the merged, time-ordered decision log."""
    with _active_lock:
        routers = list(_active)
    docs = [r.to_doc() for r in routers]
    decisions = sorted((d for doc in docs for d in doc["decisions"]),
                       key=lambda d: d["ts"])
    return {"routers": docs, "decisions": decisions}


class _Tenant:
    """Per-model runtime bundle: spec + oracle verdict + scaler +
    fleet controller (+ optional admission controller)."""

    def __init__(self, spec: ModelSpec, verdict, controller,
                 admission):
        self.spec = spec
        self.verdict = verdict
        self.controller = controller
        self.admission = admission
        self.stream = controller.stream


class ModelRouter:
    """Run one serving fleet per routed model.

    ``specs`` is a list of :class:`ModelSpec` (or the raw
    ``ZOO_SERVING_MODELS`` string).  ``features`` maps model name →
    the serving cost-model rows handed to ``choose_serving`` (e.g.
    from :func:`~analytics_zoo_tpu.analysis.costmodel
    .load_serving_rows`); models without features skip the oracle and
    start reactively at ``min_replicas``.  ``model_factory(spec)``
    builds the model a thread replica serves; ``helper_factory(spec,
    verdict)`` builds the per-model
    :class:`~analytics_zoo_tpu.serving.server.ClusterServingHelper`
    (default: batch budget from the oracle verdict when one exists).
    """

    def __init__(self, broker, specs, model_factory=None,
                 helper_factory=None, oracle=None, features=None,
                 admission: bool = False, slo_engine=None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 interval: float = 1.0,
                 fleet_interval: float | None = None,
                 mode: str = "thread", serve_log: str | None = None,
                 broker_spec=None, admission_kwargs=None,
                 controller_kwargs=None, registry=None,
                 log_capacity: int = 256):
        if isinstance(specs, str):
            specs = parse_model_specs(specs)
        specs = list(specs)
        if not specs:
            raise ValueError("ModelRouter needs at least one ModelSpec")
        self.db = connect_broker(broker)
        self.specs = specs
        self.model_factory = model_factory
        self.helper_factory = helper_factory
        self.oracle = oracle
        self.features = dict(features or {})
        self.admission_enabled = bool(admission)
        self.slo_engine = slo_engine
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval = float(interval)
        self.fleet_interval = float(
            fleet_interval if fleet_interval is not None else interval)
        self.mode = mode
        self.serve_log = serve_log
        self.broker_spec = broker_spec
        self.admission_kwargs = dict(admission_kwargs or {})
        self.controller_kwargs = dict(controller_kwargs or {})
        self.metrics = RouterMetrics(registry=registry)
        self._flight = get_flight_recorder()
        self._lock = threading.Lock()
        self._tenants: dict = {}  # guarded-by: _lock
        self._decisions: deque = (  # guarded-by: _lock
            deque(maxlen=int(log_capacity)))
        self._prev_replicas: dict = {}  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop_evt = threading.Event()
        with _active_lock:
            _active.add(self)

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, broker, **kwargs):
        """Build from a :class:`~analytics_zoo_tpu.common.engine
        .ZooConfig`: ``ZOO_SERVING_MODELS`` declares the tenants,
        ``ZOO_ADMISSION`` turns on front-door shedding, and the
        ``ZOO_FLEET_*`` tier bounds every per-model scaler."""
        specs = parse_model_specs(cfg.serving_models)
        kwargs.setdefault("admission", cfg.admission)
        kwargs.setdefault("min_replicas", cfg.fleet_min_replicas)
        kwargs.setdefault("max_replicas", cfg.fleet_max_replicas)
        kwargs.setdefault("interval", cfg.fleet_interval)
        return cls(broker, specs, **kwargs)

    # ------------------------------------------------------------------
    # per-model assembly
    # ------------------------------------------------------------------
    def _default_helper(self, spec: ModelSpec, verdict) -> \
            ClusterServingHelper:
        over = {}
        if self.broker_spec:
            over["broker"] = self.broker_spec
        if verdict and verdict.get("batch_budget_ms"):
            over["batch_budget_ms"] = float(verdict["batch_budget_ms"])
        if verdict and verdict.get("pad_buckets"):
            # the largest feasible pad bucket caps the batch: bigger
            # batches would blow the oracle's predicted service time
            over["batch_size"] = int(max(verdict["pad_buckets"]))
        return ClusterServingHelper(**over)

    def _build_tenant(self, spec: ModelSpec) -> _Tenant:
        name = spec.name
        verdict = None
        feats = self.features.get(name)
        if self.oracle is not None and feats is not None:
            verdict = self.oracle.choose_serving(
                feats, slo_p99_ms=spec.slo_p99_ms,
                offered_rate=spec.offered_rate, model=name,
                max_replicas=self.max_replicas)
        scaler = SloScaler(
            slo_p99_ms=spec.slo_p99_ms,
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            prior_target=verdict["replicas"] if verdict else None)
        helper = (self.helper_factory(spec, verdict)
                  if self.helper_factory is not None
                  else self._default_helper(spec, verdict))
        factory = None
        if self.model_factory is not None:
            factory = lambda spec=spec: self.model_factory(spec)  # noqa: E731
        stream = model_stream(name)
        ctrl = FleetController(
            helper, self.db, model_factory=factory, scaler=scaler,
            interval=self.fleet_interval, mode=self.mode,
            serve_log=self.serve_log, broker_spec=self.broker_spec,
            stream=stream, trim=not self.admission_enabled,
            **self.controller_kwargs)
        adm = None
        if self.admission_enabled:
            kw = dict(self.admission_kwargs)
            kw.setdefault("slo_engine", self.slo_engine)
            adm = AdmissionController(self.db, stream=stream,
                                      model=name, **kw)
        return _Tenant(spec, verdict, ctrl, adm)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "ModelRouter":
        """Pick configs, prime fleets, open front doors, start the
        telemetry tick (idempotent)."""
        with self._lock:
            started = bool(self._tenants)
        if not started:
            for spec in self.specs:
                t = self._build_tenant(spec)
                with self._lock:
                    self._tenants[spec.name] = t
                if t.admission is not None:
                    t.admission.start()
                t.controller.start()
                primed = t.verdict is not None and \
                    t.verdict["replicas"] > self.min_replicas
                self._record_decision(
                    spec.name, "prime" if primed else "start",
                    detail={
                        "replicas": t.controller.replica_count(),
                        "pad_buckets": (t.verdict or {}).get(
                            "pad_buckets"),
                        "batch_budget_ms": (t.verdict or {}).get(
                            "batch_budget_ms"),
                        "quantize": (t.verdict or {}).get("quantize"),
                        "admission": t.admission is not None,
                    })
        self.metrics.models.set(len(self.specs))
        self._stop_evt.clear()
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-router")
            th = self._thread
        th.start()
        return self

    def stop(self) -> None:
        """Stop the tick, every admission controller (clearing its
        published verdict), then every fleet (clean shutdown: in-flight
        claims requeued)."""
        self._stop_evt.set()
        with self._lock:
            th = self._thread
        if th is not None:
            th.join(timeout=10.0)
        with self._lock:
            tenants = list(self._tenants.values())
        for t in tenants:
            if t.admission is not None:
                t.admission.stop()
            t.controller.stop()
            self._record_decision(t.spec.name, "stop",
                                  detail={"replicas": 0})
        self.metrics.models.set(0)

    def _run(self):
        while not self._stop_evt.wait(self.interval):
            try:
                self.tick()
            except Exception as e:
                # the router must never take the fleets down; a policy
                # bug shows in the flight ring, not an outage
                self._flight.record_exception(e, where="router")

    # ------------------------------------------------------------------
    # one telemetry window
    # ------------------------------------------------------------------
    def tick(self) -> None:
        """Refresh the per-model ``zoo_fleet_model_*`` gauges and log
        replica-count movements (the per-model scale story in ONE
        place, on top of each controller's own decision log)."""
        with self._lock:
            tenants = list(self._tenants.values())
        for t in tenants:
            name = t.spec.name
            replicas = t.controller.replica_count()
            backlog = int(self.db.unclaimed(t.stream))
            cur = t.controller.current()
            win = cur["window"]
            sig = FleetSignals(
                predict_p99_s=win["predict_p99_ms"] / 1e3,
                service_rate=win["service_rate"],
                queue_depth=win["queue_depth"],
                memory_ratio=win["memory_ratio"])
            est = t.controller.scaler.estimate_p99_s(sig)
            self.metrics.replicas.labels(model=name).set(replicas)
            self.metrics.backlog.labels(model=name).set(backlog)
            if est != float("inf"):
                self.metrics.est_p99.labels(model=name).set(est)
            with self._lock:
                prev = self._prev_replicas.get(name)
                self._prev_replicas[name] = replicas
            if prev is not None and prev != replicas:
                self._record_decision(
                    name, "scale",
                    detail={"old": prev, "new": replicas,
                            "backlog": backlog,
                            "est_p99_ms": (None if est == float("inf")
                                           else round(est * 1e3, 3))})

    def _record_decision(self, model: str, action: str, detail=None):
        row = {"ts": time.time(), "model": model, "action": action}
        if detail:
            row.update(detail)
        with self._lock:
            self._decisions.append(row)
        self.metrics.decisions.labels(model=model, action=action).inc()
        self._flight.record("router", model=model, action=action,
                            **(detail or {}))

    # ------------------------------------------------------------------
    # accessors
    # ------------------------------------------------------------------
    def tenant(self, model: str) -> _Tenant:
        with self._lock:
            try:
                return self._tenants[model]
            except KeyError:
                raise KeyError(
                    f"model {model!r} is not routed; routed models: "
                    f"{sorted(self._tenants)}") from None

    def controller(self, model: str) -> FleetController:
        return self.tenant(model).controller

    def admission(self, model: str):
        return self.tenant(model).admission

    def verdict(self, model: str):
        return self.tenant(model).verdict

    def models(self) -> list:
        return [s.name for s in self.specs]

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def decision_log(self) -> list:
        with self._lock:
            return list(self._decisions)

    def current(self) -> dict:
        with self._lock:
            tenants = list(self._tenants.values())
        models = {}
        for t in tenants:
            models[t.spec.name] = {
                "spec": t.spec.to_doc(),
                "stream": t.stream,
                "replicas": t.controller.replica_count(),
                "backlog": int(self.db.unclaimed(t.stream)),
                "verdict": t.verdict,
                "admission": (t.admission.current()
                              if t.admission is not None else None),
            }
        return {"models": models, "admission": self.admission_enabled,
                "mode": self.mode}

    def to_doc(self) -> dict:
        return {"current": self.current(),
                "decisions": self.decision_log()}
