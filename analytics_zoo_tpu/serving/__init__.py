"""Cluster Serving: always-on streaming inference (reference serving/
ClusterServing.scala:44-230 + pyzoo/zoo/serving/client.py).

The reference wires Redis streams -> Spark Structured Streaming -> a
broadcast InferenceModel -> Redis result hashes.  The TPU-native design
collapses the Spark layer: a single host process (per TPU VM) pulls
micro-batches from a stream broker, runs them through the pooled, bucketed
:class:`~analytics_zoo_tpu.pipeline.inference.InferenceModel` (one jitted
XLA executable per bucket), and writes results back.  The broker is
pluggable: in-memory (tests/embedded), file-spool (multi-process, no
external service), or Redis when the ``redis`` package is importable —
same stream/hash data model in all three.
"""

from .broker import FileBroker, InMemoryBroker, RedisBroker, connect_broker
from .client import InputQueue, OutputQueue, ServingTimeout
from .server import ClusterServing, ClusterServingHelper

__all__ = [
    "InMemoryBroker", "FileBroker", "RedisBroker", "connect_broker",
    "InputQueue", "OutputQueue", "ServingTimeout",
    "ClusterServing", "ClusterServingHelper",
    "FleetController", "SloScaler",
]


def __getattr__(name):
    # fleet/scaler lazy-load (PEP 562): the fleet control plane pulls in
    # ZooConfig (jax) — a client-only process importing the package for
    # InputQueue/OutputQueue must not pay that
    if name == "FleetController":
        from .fleet import FleetController
        return FleetController
    if name == "SloScaler":
        from .scaler import SloScaler
        return SloScaler
    raise AttributeError(name)
