"""Cluster Serving: always-on streaming inference (reference serving/
ClusterServing.scala:44-230 + pyzoo/zoo/serving/client.py).

The reference wires Redis streams -> Spark Structured Streaming -> a
broadcast InferenceModel -> Redis result hashes.  The TPU-native design
collapses the Spark layer: a single host process (per TPU VM) pulls
micro-batches from a stream broker, runs them through the pooled, bucketed
:class:`~analytics_zoo_tpu.pipeline.inference.InferenceModel` (one jitted
XLA executable per bucket), and writes results back.  The broker is
pluggable: in-memory (tests/embedded), file-spool (multi-process, no
external service), or Redis when the ``redis`` package is importable —
same stream/hash data model in all three.

The predictive serving plane (ISSUE 20) adds multi-tenant routing on
top: :class:`~analytics_zoo_tpu.serving.router.ModelRouter` runs one
oracle-primed fleet per :class:`~analytics_zoo_tpu.serving.modelspec
.ModelSpec` on per-model streams, and
:class:`~analytics_zoo_tpu.serving.admission.AdmissionController`
sheds overload at the front door (clients see the typed
:class:`~analytics_zoo_tpu.serving.client.ServingRejected`) so
accepted work keeps the exactly-once claim guarantee.
"""

from .broker import FileBroker, InMemoryBroker, RedisBroker, connect_broker
from .client import InputQueue, OutputQueue, ServingRejected, \
    ServingTimeout, model_stream
from .modelspec import ModelSpec, format_model_specs, parse_model_specs
from .server import ClusterServing, ClusterServingHelper

__all__ = [
    "InMemoryBroker", "FileBroker", "RedisBroker", "connect_broker",
    "InputQueue", "OutputQueue", "ServingTimeout", "ServingRejected",
    "model_stream", "ModelSpec", "parse_model_specs",
    "format_model_specs",
    "ClusterServing", "ClusterServingHelper",
    "FleetController", "SloScaler",
    "ModelRouter", "AdmissionController",
]


def __getattr__(name):
    # control-plane lazy-load (PEP 562): fleet/router pull in ZooConfig
    # (jax) — a client-only process importing the package for
    # InputQueue/OutputQueue must not pay that
    if name == "FleetController":
        from .fleet import FleetController
        return FleetController
    if name == "SloScaler":
        from .scaler import SloScaler
        return SloScaler
    if name == "ModelRouter":
        from .router import ModelRouter
        return ModelRouter
    if name == "AdmissionController":
        from .admission import AdmissionController
        return AdmissionController
    raise AttributeError(name)
