"""Multi-replica serving fleet with SLO-aware autoscaling.

The ROADMAP's "millions of users" serving shape: N
:class:`~analytics_zoo_tpu.serving.server.ClusterServing` replicas
against ONE broker, coordinated by nothing but the broker's
exactly-once work-claim protocol (``Broker.claim``/``extend``/
``release`` — per-record leases, so replicas never double-serve and a
dead replica's claimed-but-unserved records are re-claimed by survivors
after lease expiry), each running per-bucket continuous batching in its
reader stage.  :class:`FleetController` supervises the replicas and
ticks an :class:`~analytics_zoo_tpu.serving.scaler.SloScaler` over
rolling-window telemetry deltas (the zootune pattern): predict p99 from
``zoo_serving_predict_seconds``, service rate from
``zoo_serving_records_total``, unclaimed backlog and memory pressure
from the broker — scaling up on sustained SLO violation and down on
sustained slack.

New replicas warm-start through the shared persistent compile cache
(``ZOO_COMPILE_CACHE``, common/compile_cache.py): the bucketed predict
executables a scale-up replica needs were already compiled by the first
replica, so it serves in seconds, not minutes.

Two replica modes:

- ``mode="thread"`` (default): replicas are daemon threads in this
  process sharing the registry — full scaler signals, the bench shape.
  Works over any broker, including :class:`InMemoryBroker`.
- ``mode="process"``: replicas are subprocesses running ``python -m
  analytics_zoo_tpu.serving.fleet --replica`` against a cross-process
  broker (``dir:``/redis spec).  Kill-resilient (the lease-expiry test
  shape); scaler signals are backlog-driven — unclaimed depth plus its
  observed drain rate stand in for the replicas' predict histograms —
  until the telemetry merge plane is pointed at their /varz endpoints.

Every scale decision lands three ways (the autotune convention): the
``zoo_fleet_*`` metric family, a ``fleet_scale`` flight-recorder event,
and a bounded structured decision log served in the ``fleet`` section
of ``/varz`` (rendered as a table by ``tools/metrics_dump.py``).
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import threading
import time
import weakref
from collections import deque

from ..metrics import FleetMetrics, ServingMetrics, get_flight_recorder, \
    get_registry
from .broker import connect_broker
from .client import INPUT_STREAM
from .scaler import FleetSignals, SloScaler
from .server import ClusterServing, ClusterServingHelper

__all__ = ["FleetController", "varz_doc"]

logger = logging.getLogger("analytics_zoo_tpu")

# ---------------------------------------------------------------------------
# Live-controller registry for /varz (metrics/http.py consults
# sys.modules only — a scrape-only process never imports this module).
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[FleetController]" = (  # guarded-by: _active_lock
    weakref.WeakSet())


def varz_doc() -> dict:
    """The ``fleet`` section of ``/varz``: every live controller's
    replica/scaler state plus the merged, time-ordered decision log."""
    with _active_lock:
        ctrls = list(_active)
    docs = [c.to_doc() for c in ctrls]
    decisions = sorted((d for doc in docs for d in doc["decisions"]),
                       key=lambda d: d["ts"])
    return {"controllers": docs, "decisions": decisions}


# ---------------------------------------------------------------------------
# Replica handles
# ---------------------------------------------------------------------------


class _ThreadReplica:
    """One in-process replica: a ClusterServing on its daemon thread."""

    kind = "thread"

    def __init__(self, owner: str, server: ClusterServing):
        self.owner = owner
        self.server = server

    def alive(self) -> bool:
        t = self.server._thread
        return t is not None and t.is_alive()

    def stop(self) -> None:
        self.server.stop()


class _ProcessReplica:
    """One subprocess replica (``python -m ...serving.fleet --replica``).

    SIGTERM asks for the clean shutdown (claims requeued with
    ``done=False``); SIGKILL after a grace period — and an actual
    ``kill -9`` from outside is exactly the lease-expiry story."""

    kind = "process"

    def __init__(self, owner: str, proc: subprocess.Popen):
        self.owner = owner
        self.proc = proc

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self) -> None:
        if self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5.0)


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------


class FleetController:
    """Supervise N serving replicas + tick the SLO scaler.

    ``model_factory`` is called once per THREAD replica (return a shared
    pooled model to share executables, or a fresh one per replica);
    process replicas load ``helper.model_path`` themselves.  The
    controller never holds its lock across replica/broker calls
    (lock-order hygiene — the autotune ``_apply`` pattern).
    """

    def __init__(self, helper: ClusterServingHelper, broker,
                 model_factory=None, scaler: SloScaler | None = None,
                 interval: float = 1.0, mode: str = "thread",
                 serve_log: str | None = None, broker_spec=None,
                 registry=None, log_capacity: int = 256,
                 replica_extra_args=(), signal_source=None,
                 replica_metrics: bool = False,
                 stream: str = INPUT_STREAM, trim: bool = True):
        if mode not in ("thread", "process"):
            raise ValueError(f"mode must be thread|process, got {mode!r}")
        self.helper = helper
        self.db = connect_broker(broker)
        self.model_factory = model_factory
        self.scaler = scaler if scaler is not None else SloScaler()
        self.interval = float(interval)
        self.mode = mode
        self.serve_log = serve_log
        # process replicas need a SPEC they can re-connect from;
        # an InMemoryBroker instance cannot cross a process boundary
        self.broker_spec = broker_spec if broker_spec is not None \
            else (broker if isinstance(broker, str) else None)
        if mode == "process" and not self.broker_spec:
            raise ValueError(
                "mode='process' needs a cross-process broker spec "
                "(dir:<spool> or host:port), not a live broker object")
        self.replica_extra_args = tuple(replica_extra_args)
        # Federation tier (ISSUE 17): when a signal source is attached
        # (FederatedSignalSource over a VarzScraper-fed store) the
        # scaler runs ONLY on the scraped cross-host view — the local
        # registry window is not consulted — and the decision gains a
        # host-count output.  Process replicas then need
        # ``replica_metrics=True`` so each exports /telemetryz and
        # publishes its URL for scraper discovery.
        self.signal_source = signal_source
        self.replica_metrics = bool(replica_metrics)
        # multi-tenant routing (ISSUE 20): one controller serves ONE
        # stream; a ModelRouter runs a controller per model stream.
        # trim=False for admission-guarded streams — overload is shed
        # at the front door, accepted records are never dropped.
        self.stream = str(stream)
        self.trim = bool(trim)
        self.metrics = FleetMetrics(registry=registry)
        # scaler signal sources: the SAME registry children the serving
        # replicas record into (thread mode) — family names resolve to
        # shared children
        reg = registry if registry is not None else get_registry()
        self._serving = ServingMetrics(registry=reg)

        self._lock = threading.Lock()
        self._replicas: list = []  # guarded-by: _lock
        # oracle-primed fleets START at the scaler's seeded prior
        # (initial_target == min_replicas when no prior was given)
        self._target = self.scaler.initial_target()  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._decisions: deque = (  # guarded-by: _lock
            deque(maxlen=int(log_capacity)))
        self._last_signals: FleetSignals = FleetSignals()  # guarded-by: _lock
        self._predict_base = None  # guarded-by: _lock
        self._records_base: float | None = None  # guarded-by: _lock
        self._window_t0: float | None = None  # guarded-by: _lock
        self._prev_depth: int | None = None  # guarded-by: _lock
        self._hosts: int | None = None  # guarded-by: _lock
        self._hosts_target: int | None = None  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._flight = get_flight_recorder()
        self._owner_prefix = "%s-%d" % (socket.gethostname(), os.getpid())
        self.metrics.replicas_target.set(self._target)
        with _active_lock:
            _active.add(self)

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, helper, broker, **kwargs):
        """Build controller + scaler from a
        :class:`~analytics_zoo_tpu.common.engine.ZooConfig` (the
        ``ZOO_FLEET_*`` / ``ZOO_SLO_P99_MS`` env tier)."""
        scaler = kwargs.pop("scaler", None) or SloScaler(
            slo_p99_ms=cfg.slo_p99_ms,
            min_replicas=cfg.fleet_min_replicas,
            max_replicas=cfg.fleet_max_replicas)
        return cls(helper, broker, scaler=scaler,
                   interval=cfg.fleet_interval, **kwargs)

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------
    def _next_owner(self) -> str:
        with self._lock:
            self._seq += 1
            return "%s-r%d" % (self._owner_prefix, self._seq)

    def _spawn(self):
        owner = self._next_owner()
        if self.mode == "thread":
            model = self.model_factory() if self.model_factory is not None \
                else self.helper.load_inference_model()
            srv = ClusterServing(helper=self.helper, model=model,
                                 broker=self.db, owner=owner,
                                 serve_log=self.serve_log,
                                 stream=self.stream, trim=self.trim)
            srv.start()
            rep = _ThreadReplica(owner, srv)
        else:
            cmd = [sys.executable, "-m",
                   "analytics_zoo_tpu.serving.fleet", "--replica",
                   "--broker", str(self.broker_spec),
                   "--owner", owner,
                   "--batch-size", str(self.helper.batch_size),
                   "--budget-ms", str(self.helper.batch_budget_ms),
                   "--lease-ms", str(self.helper.lease_ms),
                   "--stream", self.stream]
            if not self.trim:
                cmd += ["--no-trim"]
            if self.helper.model_path:
                cmd += ["--model", str(self.helper.model_path)]
            if self.serve_log:
                cmd += ["--serve-log", self.serve_log]
            if self.replica_metrics:
                # ephemeral port; the replica publishes its bound URL
                # on the broker (VARZ_KEY_PREFIX) for scraper discovery
                cmd += ["--metrics-port", "0"]
            cmd += list(self.replica_extra_args)
            rep = _ProcessReplica(owner, subprocess.Popen(cmd))
        with self._lock:
            self._replicas.append(rep)
            n = len(self._replicas)
        self.metrics.replicas.set(n)
        return rep

    def _stop_one(self):
        """Retire the NEWEST replica (LIFO): its clean shutdown requeues
        any in-flight claims with ``done=False`` — no lease wait."""
        with self._lock:
            rep = self._replicas.pop() if self._replicas else None
            n = len(self._replicas)
        if rep is not None:
            rep.stop()
            self.metrics.replicas.set(n)

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def owners(self) -> list:
        with self._lock:
            return [r.owner for r in self._replicas]

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "FleetController":
        """Spawn up to the scaler's initial target (the oracle-seeded
        prior when one exists, else ``min_replicas``) and start the
        control loop (idempotent)."""
        initial = self.scaler.initial_target()
        primed = initial > self.scaler.min_replicas \
            and self.replica_count() < initial
        while self.replica_count() < initial:
            self._spawn()
        if primed:
            self._record_decision(
                "prime", self.scaler.min_replicas, initial,
                "oracle_prior", None, 0)
        self._stop_evt.clear()
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-fleet")
            t = self._thread
        t.start()
        return self

    def stop(self) -> None:
        """Stop the control loop, then every replica (clean shutdown:
        in-flight claims are requeued, results flushed)."""
        self._stop_evt.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        while True:
            with self._lock:
                rep = self._replicas.pop() if self._replicas else None
            if rep is None:
                break
            rep.stop()
        self.metrics.replicas.set(0)

    def _run(self):
        while not self._stop_evt.wait(self.interval):
            try:
                self._tick()
            except Exception as e:
                # the controller must never take the fleet down; a
                # policy bug shows in the flight ring, not a crash
                self._flight.record_exception(e, where="fleet")

    # ------------------------------------------------------------------
    # one control window
    # ------------------------------------------------------------------
    def _gather_window(self) -> FleetSignals:
        if self.signal_source is not None:
            return self._gather_federated()
        now = time.monotonic()
        with self._lock:
            p_base = self._predict_base
            r_base = self._records_base
            t0 = self._window_t0
            prev_depth = self._prev_depth
        hist = self._serving.predict_latency
        delta = hist.delta_since(p_base)
        records = self._serving.records.get()
        new_p_base = hist.snapshot_state()
        depth = int(self.db.unclaimed(self.stream))
        rate = 0.0
        if r_base is not None and t0 is not None and now > t0:
            rate = max(0.0, records - r_base) / (now - t0)
            if rate == 0.0 and not delta.get("count") \
                    and prev_depth is not None:
                # process-mode replicas record into THEIR registries,
                # not ours — fall back to the observable backlog drain
                # rate so a healthily-draining fleet is not mistaken
                # for a stalled one (est=inf) and scaled to max
                rate = max(0.0, prev_depth - depth) / (now - t0)
        with self._lock:
            self._predict_base = new_p_base
            self._records_base = records
            self._window_t0 = now
            self._prev_depth = depth
        sig = FleetSignals(
            predict_p99_s=float(delta.get("p99", 0.0) or 0.0),
            window_count=int(delta.get("count", 0) or 0),
            service_rate=rate,
            queue_depth=depth,
            memory_ratio=float(self.db.memory_ratio()),
        )
        if p_base is None:
            # first window: baseline only, report an idle signal
            sig = FleetSignals(queue_depth=sig.queue_depth,
                               memory_ratio=sig.memory_ratio)
        return sig

    def _gather_federated(self) -> FleetSignals:
        """Federated window: the LOCAL registry is not consulted — the
        signal source reads the scraped per-host series (ISSUE 17).
        The window spans the elapsed time since the previous tick, so
        the store's delta covers exactly one control interval."""
        now = time.monotonic()
        with self._lock:
            t0 = self._window_t0
            self._window_t0 = now
        window_s = max(self.interval,
                       (now - t0) if t0 is not None else self.interval)
        return self.signal_source.gather(window_s)

    def _supervise(self) -> int:
        """Drop dead replicas (their leases expire to survivors) and
        respawn to target; returns live count."""
        with self._lock:
            dead = [r for r in self._replicas if not r.alive()]
            for r in dead:
                self._replicas.remove(r)
            n, target = len(self._replicas), self._target
        if dead:
            self.metrics.replica_deaths.inc(len(dead))
            self.metrics.replicas.set(n)
            for r in dead:
                self._flight.record("fleet_replica_death", owner=r.owner)
                logger.warning("fleet: replica %s died; records it "
                               "claimed re-serve after lease expiry",
                               r.owner)
        while n < target and not self._stop_evt.is_set():
            self._spawn()
            self._record_decision("replace", n, n + 1, "supervision",
                                  None, 0)
            n += 1
        return n

    def _tick(self):
        n = self._supervise()
        sig = self._gather_window()
        est = self.scaler.estimate_p99_s(sig)
        if est != float("inf"):
            # inf (stalled backlog) would be JSON-hostile in /varz and
            # misleading as 0 — the decision log carries the event
            self.metrics.est_p99.set(est)
        self.metrics.queue_depth.set(sig.queue_depth)
        if est > self.scaler.slo_p99_ms / 1e3:
            self.metrics.slo_violations.inc()
        hosts = hosts_target = None
        if self.signal_source is not None:
            hosts = max(1, int(self.signal_source.host_count()))
            target, hosts_target, reason = self.scaler.decide_fleet(
                n, hosts, sig)
            self.metrics.hosts.set(hosts)
            self.metrics.hosts_target.set(hosts_target)
        else:
            target, reason = self.scaler.decide(n, sig)
        with self._lock:
            self._target = target
            self._last_signals = sig
            self._hosts = hosts
            self._hosts_target = hosts_target
        self.metrics.replicas_target.set(target)
        if target == n:
            return
        action = "up" if target > n else "down"
        self._record_decision(action, n, target, reason, est,
                              sig.queue_depth, hosts=hosts,
                              hosts_target=hosts_target)
        while n < target and not self._stop_evt.is_set():
            self._spawn()
            n += 1
        while n > target and not self._stop_evt.is_set():
            self._stop_one()
            n -= 1

    def _record_decision(self, action, old, new, reason, est_p99_s,
                         queue_depth, hosts=None, hosts_target=None):
        est_ms = None if est_p99_s is None or est_p99_s != est_p99_s \
            or est_p99_s == float("inf") else round(est_p99_s * 1e3, 3)
        row = {"ts": time.time(), "action": action, "old": old,
               "new": new, "reason": reason, "est_p99_ms": est_ms,
               "queue_depth": queue_depth}
        if hosts is not None:
            row["hosts"] = hosts
            row["hosts_target"] = hosts_target
        with self._lock:
            self._decisions.append(row)
        self.metrics.decisions.labels(action=action, reason=reason).inc()
        self._flight.record("fleet_scale", action=action, old=old,
                            new=new, reason=reason, est_p99_ms=est_ms,
                            queue_depth=queue_depth,
                            **({"hosts": hosts,
                                "hosts_target": hosts_target}
                               if hosts is not None else {}))

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def decision_log(self) -> list:
        with self._lock:
            return list(self._decisions)

    def current(self) -> dict:
        with self._lock:
            sig = self._last_signals
            return {
                "replicas": len(self._replicas),
                "target": self._target,
                "owners": [r.owner for r in self._replicas],
                "mode": self.mode,
                "stream": self.stream,
                "federated": self.signal_source is not None,
                "hosts": self._hosts,
                "hosts_target": self._hosts_target,
                "slo_p99_ms": self.scaler.slo_p99_ms,
                "min_replicas": self.scaler.min_replicas,
                "max_replicas": self.scaler.max_replicas,
                "window": {
                    "predict_p99_ms": round(sig.predict_p99_s * 1e3, 3),
                    "service_rate": round(sig.service_rate, 3),
                    "queue_depth": sig.queue_depth,
                    "memory_ratio": round(sig.memory_ratio, 4),
                },
            }

    def to_doc(self) -> dict:
        return {"current": self.current(), "decisions": self.decision_log()}


# ---------------------------------------------------------------------------
# Subprocess replica entry point:
#   python -m analytics_zoo_tpu.serving.fleet --replica --broker dir:...
# ---------------------------------------------------------------------------


class _SyntheticModel:
    """Load-test stand-in model: per-RECORD service time, GIL-releasing
    (time.sleep), fixed 5-logit output — the bench/kill-test workload
    when no real model path is given."""

    def __init__(self, sleep_ms_per_record: float, classes: int = 5):
        self.sleep_s = float(sleep_ms_per_record) / 1e3
        self.classes = int(classes)

    def predict(self, arr):
        import numpy as np

        if self.sleep_s > 0:
            time.sleep(self.sleep_s * int(arr.shape[0]))
        out = np.zeros((int(arr.shape[0]), self.classes), np.float32)
        out[:, 0] = 1.0
        return out


def _replica_main(argv) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(
        prog="analytics_zoo_tpu.serving.fleet",
        description="run ONE fleet replica against a shared broker")
    p.add_argument("--replica", action="store_true", required=True)
    p.add_argument("--broker", required=True,
                   help="cross-process broker spec (dir:<spool>, "
                        "host:port)")
    p.add_argument("--owner", default=None)
    p.add_argument("--model", default=None, help="model path; omit to "
                   "serve the synthetic sleep model")
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--budget-ms", type=float, default=None)
    p.add_argument("--lease-ms", type=int, default=None)
    p.add_argument("--synthetic-sleep-ms", type=float, default=0.0,
                   help="per-record service time of the synthetic model")
    p.add_argument("--serve-log", default=None)
    p.add_argument("--stream", default=INPUT_STREAM,
                   help="input stream to claim from (per-model streams "
                        "under the router)")
    p.add_argument("--no-trim", action="store_true",
                   help="never trim the stream under broker pressure "
                        "(admission-guarded streams shed at the front "
                        "door instead)")
    p.add_argument("--idle-timeout", type=float, default=None)
    p.add_argument("--max-records", type=int, default=None)
    p.add_argument("--metrics-port", type=int, default=None,
                   help="start a /telemetryz server on this port (0 = "
                        "ephemeral) and publish its URL on the broker "
                        "for federation-scraper discovery")
    a = p.parse_args(argv)

    owner = a.owner or "%s-%d" % (socket.gethostname(), os.getpid())
    over = {"model_path": a.model, "batch_size": a.batch_size,
            "log_dir": os.environ.get("ZOO_SERVING_LOG_DIR", ".")}
    if a.budget_ms is not None:
        over["batch_budget_ms"] = a.budget_ms
    if a.lease_ms is not None:
        over["lease_ms"] = a.lease_ms
    helper = ClusterServingHelper(broker=a.broker, **over)
    model = None if a.model else _SyntheticModel(a.synthetic_sleep_ms)
    srv = ClusterServing(helper=helper, model=model, owner=owner,
                         serve_log=a.serve_log, stream=a.stream,
                         trim=not a.no_trim)
    metrics_srv, varz_db = None, None
    if a.metrics_port is not None:
        # federated replica: export this process's registry at
        # /telemetryz and register the bound URL under the discovery
        # key — the controller-side VarzScraper finds it there.  A bind
        # failure degrades to an undiscoverable (but serving) replica.
        from analytics_zoo_tpu.metrics.http import MetricsServer
        from analytics_zoo_tpu.metrics.scrape import VARZ_KEY_PREFIX

        try:
            metrics_srv = MetricsServer(port=a.metrics_port).start()
            varz_db = connect_broker(a.broker)
            varz_db.hset(VARZ_KEY_PREFIX + owner,
                         {"url": metrics_srv.url, "ts": str(time.time())})
        except OSError:
            metrics_srv = None
    signal.signal(signal.SIGTERM, lambda *_: srv.stop())
    try:
        srv.run(max_records=a.max_records, idle_timeout=a.idle_timeout)
    finally:
        if varz_db is not None:
            try:
                varz_db.delete(VARZ_KEY_PREFIX + owner)
            except Exception:
                pass  # a dying replica just leaves a stale key; the
                # scraper's staleness verdict handles it
        if metrics_srv is not None:
            metrics_srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(_replica_main(sys.argv[1:]))
