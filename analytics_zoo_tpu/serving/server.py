"""Cluster Serving server (reference serving/ClusterServing.scala:44-230 and
serving/utils/ClusterServingHelper.scala).

The cycle: read up to ``batch_size`` records from the input stream, decode,
stack into one micro-batch, run the pooled/bucketed InferenceModel (one
jitted XLA executable per batch bucket — device math stays on TPU), write
per-uri result hashes back, apply backpressure by trimming the stream when
the broker is near memory capacity (ClusterServing.scala:126-134).

:meth:`ClusterServing.run` executes that cycle as a THREE-STAGE PIPELINE
(the default): a broker-reader thread polls + acks + decodes the next
micro-batch (decode fanned out on a small pool) while the current one is
in ``model.predict`` on the main loop, and a write-back thread drains a
bounded result queue — broker I/O and host decode fully overlap device
inference, the serving-side analogue of the estimator's double-buffered
infeed.  Result write-back is batched: ONE ``hset_many`` broker
round-trip per micro-batch instead of one ``hset`` per record.
``run(pipelined=False)`` keeps the strictly serial
read→decode→predict→write cycle (:meth:`step`).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..metrics import (
    FleetMetrics,
    ServingMetrics,
    StragglerDetector,
    get_flight_recorder,
    get_health,
    maybe_start_from_env,
    span,
)
from ..tensorboard import InferenceSummary
from .broker import connect_broker
from .client import INPUT_STREAM, RESULT_PREFIX, decode_ndarray, \
    encode_ndarray

logger = logging.getLogger("analytics_zoo_tpu")

# Continuous-batching latency budget (ms): how long a PARTIAL shape
# bucket may wait for co-batchable arrivals before it is flushed to
# predict.  0 disables holding (every claim batch flushes immediately).
DEFAULT_BATCH_BUDGET_MS = 25.0
# Fleet work-claim lease (ms): a replica silent for this long forfeits
# its claimed-but-unserved records to the survivors.
DEFAULT_LEASE_MS = 10_000


def _env_number(name: str, default, cast, minimum):
    """Eager-validated numeric env knob (the ZooConfig resolve_int
    pattern, available here without importing the jax-backed engine):
    a bad value fails at server construction naming the env var."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        val = cast(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{name} must be a number >= {minimum}, got {raw!r}") from None
    if val < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {val}")
    return val


class ClusterServingHelper:
    """Config holder (reference ClusterServingHelper.scala yaml schema:
    model path, data shape, batch size, top_n, redis host/port)."""

    def __init__(self, config_path: str | None = None, **overrides):
        cfg = {}
        if config_path:
            import yaml
            with open(config_path) as f:
                cfg = yaml.safe_load(f) or {}
        model = cfg.get("model", {}) or {}
        params = cfg.get("params", {}) or {}
        data = cfg.get("data", {}) or {}
        self.model_path = overrides.get("model_path", model.get("path"))
        self.batch_size = int(overrides.get(
            "batch_size", params.get("batch_size", 4)))
        self.top_n = int(overrides.get("top_n", params.get("top_n", 1)))
        self.data_shape = overrides.get("data_shape",
                                        data.get("image_shape"))
        if isinstance(self.data_shape, str):
            self.data_shape = tuple(
                int(v) for v in self.data_shape.split(","))
        src = data.get("src", "localhost:6379")
        self.broker_spec = overrides.get("broker", src)
        self.log_dir = overrides.get("log_dir", cfg.get("log_dir", "."))
        # reference filter spec, e.g. "topN(5)" — wired into postprocess
        self.filter = overrides.get("filter", params.get("filter"))
        if isinstance(self.filter, str) and self.filter.startswith("topN("):
            self.top_n = int(self.filter[5:].rstrip(")"))
        # Fleet knobs (claim-mode serving): continuous-batching budget +
        # work-claim lease.  Precedence: explicit override > yaml params
        # > env (ZOO_SERVING_BATCH_BUDGET_MS / ZOO_FLEET_LEASE_MS) >
        # default — the ZooConfig env-tier contract, validated eagerly.
        budget = overrides.get("batch_budget_ms",
                               params.get("batch_budget_ms"))
        if budget is None:  # env parsed only when nothing overrides it
            budget = _env_number("ZOO_SERVING_BATCH_BUDGET_MS",
                                 DEFAULT_BATCH_BUDGET_MS, float, 0.0)
        self.batch_budget_ms = float(budget)
        if self.batch_budget_ms < 0:
            raise ValueError(
                f"batch_budget_ms must be >= 0, got {self.batch_budget_ms}")
        lease = overrides.get("lease_ms", params.get("lease_ms"))
        if lease is None:
            lease = _env_number("ZOO_FLEET_LEASE_MS", DEFAULT_LEASE_MS,
                                int, 100)
        self.lease_ms = int(lease)
        if self.lease_ms < 100:
            raise ValueError(
                f"lease_ms must be >= 100 (shorter leases expire inside "
                f"one broker round-trip), got {self.lease_ms}")

    def load_inference_model(self):
        from ..pipeline.inference import InferenceModel
        m = InferenceModel(concurrent_num=1)
        m.load(self.model_path)
        return m


class _BucketBatcher:
    """Per-shape continuous batching for the fleet reader.

    Decoded records are admitted into the in-flight bucket for their
    SHAPE; a bucket flushes when it reaches ``batch_size`` (reason
    ``full``) or when its oldest record has waited ``budget_s`` seconds
    (reason ``budget``) — a lone request is served within the latency
    budget instead of waiting for co-batchable traffic that may never
    come, while a trickle of same-shape requests coalesces into one
    padded predict.  Flushed batches never exceed ``batch_size``, so
    they land in exactly the power-of-two pad buckets the fixed
    micro-batch path compiles — continuous batching adds NO new XLA
    executables.  Single-thread use (the reader owns it); no locks."""

    def __init__(self, batch_size: int, budget_s: float):
        self.batch_size = max(1, int(batch_size))
        self.budget_s = max(0.0, float(budget_s))
        # shape -> list of (rid, uri, arr, t_admit)
        self._pending: dict = {}

    def add(self, rid: str, uri: str, arr, now: float) -> None:
        self._pending.setdefault(arr.shape, []).append(
            (rid, uri, arr, now))

    def __len__(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def next_deadline(self) -> float | None:
        """Monotonic time of the nearest bucket flush, or None when
        nothing is pending (the reader bounds its claim block on this
        so a partial bucket is flushed ON its budget, not up to one
        poll interval late)."""
        oldest = [recs[0][3] for recs in self._pending.values() if recs]
        return min(oldest) + self.budget_s if oldest else None

    def _chunk(self, shape, reason: str):
        recs = self._pending[shape][:self.batch_size]
        del self._pending[shape][:self.batch_size]
        if not self._pending[shape]:
            del self._pending[shape]
        ids = [r[0] for r in recs]
        uris = [r[1] for r in recs]
        arrs = [r[2] for r in recs]
        return ids, uris, arrs, reason

    def take_ready(self, now: float) -> list:
        """Flush full buckets, and partial buckets past their budget."""
        out = []
        for shape in list(self._pending):
            while len(self._pending.get(shape, ())) >= self.batch_size:
                out.append(self._chunk(shape, "full"))
            recs = self._pending.get(shape)
            if recs and now - recs[0][3] >= self.budget_s:
                out.append(self._chunk(shape, "budget"))
        return out

    def take_all(self) -> list:
        """Drain everything (shutdown path)."""
        out = []
        for shape in list(self._pending):
            while shape in self._pending:
                out.append(self._chunk(shape, "drain"))
        return out


class ClusterServing:
    """The serving main loop (reference ClusterServing.main)."""

    # backpressure thresholds (ClusterServing.scala:126-128)
    INPUT_THRESHOLD = 0.6 * 0.8
    CUT_RATIO = 0.5

    def __init__(self, helper: ClusterServingHelper | None = None,
                 model=None, broker=None, config_path: str | None = None,
                 owner: str | None = None, serve_log: str | None = None,
                 stream: str = INPUT_STREAM, trim: bool = True,
                 **overrides):
        self.helper = helper or ClusterServingHelper(config_path,
                                                     **overrides)
        self.db = connect_broker(broker if broker is not None
                                 else self.helper.broker_spec)
        # Multi-tenant routing (ISSUE 20): which stream this server
        # polls/claims.  The default is the single-tenant input stream;
        # the router runs one fleet per model stream.
        self.stream = str(stream)
        # trim=False: the stream is admission-guarded (serving/
        # admission.py sheds at the FRONT door), so the overload valve
        # must never drop records that were already accepted — the
        # exactly-once guarantee covers them.  Default True preserves
        # the unguarded backpressure behavior (scala parity).
        self.trim = bool(trim)
        self.model = model if model is not None \
            else self.helper.load_inference_model()
        # Fleet replica identity (serving/fleet.py): when set, run()
        # CLAIMS records under a lease instead of reading by cursor —
        # N owners against one broker never double-serve, and this
        # replica's death forfeits its in-flight claims to survivors
        # after helper.lease_ms.  Continuous batching rides the same
        # mode (helper.batch_budget_ms).
        self.owner = owner
        # Optional serve audit log: one "<owner> <uri>" line appended
        # AFTER each batch's results are durable and its claims
        # released — the exactly-once ledger the fleet tests (and any
        # delivery audit) read.
        self.serve_log = serve_log
        self.summary = InferenceSummary(
            self.helper.log_dir,
            time.strftime("%Y%m%d-%H%M%S") + "-ClusterServing")
        self._last_id = "0"
        self._stop = threading.Event()
        self._thread = None
        self.total_count = 0
        # Serving telemetry (metrics/): queue depth, batch size, latency
        # histograms per step() — no-op singletons when ZOO_METRICS=0.
        self.metrics = ServingMetrics()
        # Flight recorder + straggler detector (ISSUE 2): non-empty
        # cycles land in the bounded ring; a crashed step's final events
        # survive at /flightz and in the ZOO_FLIGHT_DIR dump.
        self._flight = get_flight_recorder()
        self._straggler = StragglerDetector()

    # ------------------------------------------------------------------

    def _postprocess(self, uri: str, out: np.ndarray) -> dict:
        """Top-N (class, prob) json for vectors, tensor payload otherwise
        (reference writes top-N class records back to redis).  The
        original uri rides along so dequeue() can key results on it even
        over transports whose key names are mangled (FileBroker)."""
        out = np.asarray(out)
        if out.ndim == 1 and self.helper.top_n:
            n = min(self.helper.top_n, out.shape[0])
            top = np.argsort(out)[::-1][:n]
            return {"uri": uri, "value": json.dumps(
                [[int(i), float(out[i])] for i in top])}
        return {"uri": uri, "tensor": encode_ndarray(out)}

    def _decode_one(self, rid: str, fields: dict):
        """One record -> ndarray, or None (logged) when undecodable or
        mis-shaped.  Pure per-record work — safe to fan out on a pool."""
        try:
            arr = decode_ndarray(fields["image"])
        except Exception:
            logger.warning("serving: undecodable record %s", rid)
            return None
        if self.helper.data_shape and \
                tuple(arr.shape) != tuple(self.helper.data_shape):
            logger.warning("serving: shape %s != expected %s (uri=%s)",
                           arr.shape, self.helper.data_shape,
                           fields.get("uri"))
            return None
        return arr

    def _decode_records(self, records, pool=None):
        """records -> (uris, arrs), bad records dropped.  With ``pool``
        the per-record base64+npy decode runs across pool threads (order
        preserved — Executor.map)."""
        if pool is not None:
            decoded = list(pool.map(
                lambda rf: self._decode_one(rf[0], rf[1]), records))
        else:
            decoded = [self._decode_one(rid, f) for rid, f in records]
        uris, arrs = [], []
        for (rid, fields), arr in zip(records, decoded):
            if arr is None:
                continue
            uris.append(fields.get("uri", rid))
            arrs.append(arr)
        return uris, arrs

    @staticmethod
    def _group_by_shape(uris, arrs) -> dict:
        # group by shape: with no configured data_shape, clients may send
        # mixed sizes; each group becomes one stacked micro-batch
        groups: dict = {}
        for uri, arr in zip(uris, arrs):
            groups.setdefault(arr.shape, ([], []))
            groups[arr.shape][0].append(uri)
            groups[arr.shape][1].append(arr)
        return groups

    # zoolint: hot-path
    def _predict_groups(self, groups) -> list:
        """Run predict per shape group; return the [(key, mapping)]
        write-back list for ONE batched broker round-trip."""
        writes = []
        for g_uris, g_arrs in groups.values():
            with self.metrics.predict_latency.time(), \
                    span("zoo.serving.predict",
                         args={"batch": len(g_uris)}):
                preds = self.model.predict(np.stack(g_arrs))
            if isinstance(preds, list):  # multi-output: report first head
                preds = preds[0]
            # zoolint: disable=host-sync -- predictions must land on host for write-back; the pipelined writer overlaps it
            for uri, out in zip(g_uris, np.asarray(preds)):
                writes.append((RESULT_PREFIX + uri,
                               self._postprocess(uri, out)))
        return writes

    def process_batch(self, records) -> int:
        if not records:
            return 0
        uris, arrs = self._decode_records(records)
        if not arrs:
            return 0
        t0 = time.perf_counter()
        writes = self._predict_groups(self._group_by_shape(uris, arrs))
        # one broker round-trip per micro-batch (hset_many pipelines or
        # falls back per-broker), not one hset per record
        self.db.hset_many(writes)
        dt = time.perf_counter() - t0
        self.total_count += len(uris)
        self.summary.add_scalar("Throughput", len(uris) / max(dt, 1e-9),
                                self.total_count)
        logger.info("serving: batch of %d in %.1f ms", len(uris), dt * 1e3)
        return len(uris)

    # zoolint: hot-path
    def step(self, block_ms: int = 100) -> int:
        """One poll + predict + write-back cycle; returns #records served."""
        ratio = self.db.memory_ratio()
        self.metrics.memory_ratio.set(ratio)
        if self.trim and ratio >= self.INPUT_THRESHOLD:
            # zoolint: disable=host-sync -- broker-side host integer, no device involved
            keep = int(self.db.xlen(self.stream) * self.CUT_RATIO)
            self.db.xtrim(self.stream, keep)
            self.metrics.trims.inc()
        records = self.db.xread(self.stream, self.helper.batch_size,
                                last_id=self._last_id, block_ms=block_ms)
        t0 = time.perf_counter()
        if records:
            self._last_id = records[-1][0]
        try:
            if records:
                # span only on non-empty cycles: an idle loop at
                # block_ms=100 would otherwise flood the bounded tracer
                # with ~10 zero-information events/sec
                with span("zoo.serving.step"):
                    n = self.process_batch(records)
            else:
                n = 0
        except BaseException as e:
            # a crashed step's last act: land in the flight ring, so
            # /flightz and the ZOO_FLIGHT_DIR dump show WHICH batch died
            self._flight.record_exception(e, where="serving.step")
            raise
        finally:
            if records:
                # ack consumed records so the stream cannot grow unbounded
                self.db.ack(self.stream, self._last_id)
        # service latency endpoint taken BEFORE any metrics-only broker
        # traffic below, so enabling metrics cannot inflate the very
        # latency being measured
        t_end = time.perf_counter()
        # true backlog: what remains AFTER this cycle's records were
        # acked — the xlen is an extra broker round-trip, so it only
        # runs when metrics are on and this cycle actually served
        # (an empty poll means the backlog was already drained)
        if records and self.metrics.enabled:
            self.metrics.queue_depth.set(self.db.xlen(self.stream))
        if records:
            # service latency for this cycle: decode + batch formation +
            # predict + write-back (poll wait excluded — the records
            # arrived by t0).  Queueing delay before the poll shows up in
            # queue_depth, not here.
            self._record_cycle(len(records), n, t_end - t0)
        return n

    def _record_cycle(self, n_read: int, n_served: int, dt: float):
        """Per-cycle telemetry shared by the serial step() and the
        pipelined loop: latency/batch-size/served metrics, the flight
        ring record (non-empty cycles only — an idle poll would flood
        the postmortem window), and straggler detection."""
        self.metrics.latency.observe(dt)
        self.metrics.batch_size.observe(n_read)
        self.metrics.records.inc(n_served)
        self._flight.record(
            "step", loop="serving", records=n_read, served=n_served,
            latency_s=round(dt, 6))
        if self._straggler.observe(dt):
            self.metrics.stragglers.inc()
            self._flight.record(
                "straggler", loop="serving", latency_s=round(dt, 6),
                rolling_p50_s=round(self._straggler.rolling_p50(), 6))

    def run(self, max_records: int | None = None,
            idle_timeout: float | None = None,
            pipelined: bool = True) -> int:
        """Blocking serve loop.  Stops after ``max_records`` served, after
        ``idle_timeout`` seconds without input, or on :meth:`stop`.

        ``pipelined=True`` (default) runs the three-stage pipeline —
        broker read + decode, predict, write-back on separate threads so
        the stages overlap; ``False`` keeps the strictly serial
        :meth:`step` cycle."""
        # a previous run() on this server closed its summary on exit (e.g.
        # a warm-up pass before start()): open a fresh event file
        if self.summary.closed:
            self.summary = InferenceSummary(
                self.helper.log_dir,
                time.strftime("%Y%m%d-%H%M%S") + "-ClusterServing")
        # Distributed telemetry plane (ISSUE 2): scrape endpoints opt in
        # via ZOO_METRICS_PORT; crash dumps arm via ZOO_FLIGHT_DIR; the
        # loop heartbeats /healthz every cycle (even idle polls — an
        # idle loop is alive; a WEDGED one goes 503 after 15s).
        maybe_start_from_env()
        self._flight.install()
        health = get_health()
        # 120s budget: one beat per cycle, and the first non-empty batch
        # pays the bucketed XLA compile — tens of seconds on big models;
        # /healthz must not 503 a process that is compiling, only one
        # that stopped cycling.
        health.register("serving_loop", stale_after=120.0)
        try:
            if self.owner is not None:
                # fleet replica: claim-based exactly-once loop with
                # continuous batching (always pipelined — the claim
                # protocol lives in the reader/writer stages)
                return self._run_fleet(max_records, idle_timeout, health)
            if pipelined:
                return self._run_pipelined(max_records, idle_timeout,
                                           health)
            return self._run_serial(max_records, idle_timeout, health)
        finally:
            health.unregister("serving_loop")  # stopped on purpose
            self.summary.close()

    def _run_serial(self, max_records, idle_timeout, health) -> int:
        served = 0
        last_active = time.monotonic()
        while not self._stop.is_set():
            try:
                n = self.step()
            except Exception:
                # a bad batch must not kill the serving loop/thread
                logger.exception("serving: batch failed; continuing")
                n = 0
            health.heartbeat("serving_loop")
            served += n
            if n:
                last_active = time.monotonic()
            if max_records is not None and served >= max_records:
                break
            if idle_timeout is not None and \
                    time.monotonic() - last_active > idle_timeout:
                break
        return served

    _PIPE_DEPTH = 2  # decoded micro-batches buffered ahead of predict

    # zoolint: hot-path
    def _run_pipelined(self, max_records, idle_timeout, health) -> int:
        """Three-stage pipeline: reader(poll+ack+decode) → predict →
        writer(batched hset_many).  Bounded queues between stages keep
        memory flat and deliver backpressure; a ``done`` event local to
        this run lets max_records/idle exits leave the server
        restartable (self._stop stays the external kill switch)."""
        in_q: queue.Queue = queue.Queue(maxsize=self._PIPE_DEPTH)
        out_q: queue.Queue = queue.Queue(maxsize=self._PIPE_DEPTH * 2)
        done = threading.Event()
        end = object()  # pipe sentinel
        decode_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="zoo-serving-decode")

        def stopped():
            return done.is_set() or self._stop.is_set()

        def bput(q, item) -> bool:
            while not stopped():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def reader():
            health.register("serving_reader", stale_after=120.0)
            try:
                while not stopped():
                    try:
                        ratio = self.db.memory_ratio()
                        self.metrics.memory_ratio.set(ratio)
                        if self.trim and ratio >= self.INPUT_THRESHOLD:
                            # zoolint: disable=host-sync -- broker-side host integer, no device involved
                            keep = int(self.db.xlen(self.stream)
                                       * self.CUT_RATIO)
                            self.db.xtrim(self.stream, keep)
                            self.metrics.trims.inc()
                        records = self.db.xread(
                            self.stream, self.helper.batch_size,
                            last_id=self._last_id, block_ms=100)
                        health.heartbeat("serving_reader")
                        if not records:
                            continue
                        # advance the READ cursor only; the ack happens in
                        # the writer AFTER the batch's results are flushed,
                        # so a batch dropped by shutdown mid-pipeline stays
                        # in the stream (and the cursor rewind below makes
                        # the next run() re-read it)
                        self._last_id = records[-1][0]
                        uris, arrs = self._decode_records(
                            records, pool=decode_pool)
                        if self.metrics.enabled:
                            self.metrics.queue_depth.set(
                                self.db.xlen(self.stream))
                        if not bput(in_q, (len(records), self._last_id,
                                           uris, arrs)):
                            return
                    except Exception:
                        # a bad poll/decode must not kill the pipeline
                        logger.exception(
                            "serving: reader failed; continuing")
                        time.sleep(0.05)
            finally:
                health.unregister("serving_reader")
                bput(in_q, end)  # no-op when the main loop already left

        def writer():
            health.register("serving_writer", stale_after=120.0)
            try:
                while True:
                    try:
                        item = out_q.get(timeout=0.5)
                    except queue.Empty:
                        # an idle server is healthy — /healthz must not
                        # 503 a pipeline that simply has no traffic
                        health.heartbeat("serving_writer")
                        continue
                    if item is end:
                        return
                    writes, upto_id = item
                    try:
                        if writes:
                            self.db.hset_many(writes)
                        # results durable (or judged unservable): NOW the
                        # records may leave the stream
                        self.db.ack(self.stream, upto_id)
                    except Exception:
                        logger.exception(
                            "serving: write-back failed; continuing")
                    health.heartbeat("serving_writer")
            finally:
                health.unregister("serving_writer")

        rt = threading.Thread(target=reader, daemon=True,
                              name="zoo-serving-reader")
        wt = threading.Thread(target=writer, daemon=True,
                              name="zoo-serving-writer")
        rt.start()
        wt.start()
        served = 0
        # the last stream id whose batch was handed to the writer: the
        # exit cursor.  Anything the reader read beyond it was neither
        # predicted nor acked, so rewinding self._last_id here makes the
        # next run() serve it instead of skipping it.
        processed_id = self._last_id
        last_active = time.monotonic()
        try:
            while not self._stop.is_set():
                try:
                    item = in_q.get(timeout=0.1)
                except queue.Empty:
                    health.heartbeat("serving_loop")
                    if idle_timeout is not None and \
                            time.monotonic() - last_active > idle_timeout:
                        break
                    continue
                if item is end:
                    break
                n_read, batch_last_id, uris, arrs = item
                t0 = time.perf_counter()
                n = 0
                writes = []
                try:
                    if arrs:
                        with span("zoo.serving.step"):
                            writes = self._predict_groups(
                                self._group_by_shape(uris, arrs))
                        n = len(uris)
                except Exception as e:
                    self._flight.record_exception(e, where="serving.step")
                    logger.exception("serving: batch failed; continuing")
                    writes = []  # failed batch: ack it (serial parity)
                # always hand the batch to the writer — even an all-bad or
                # failed batch must be acked once its fate is sealed
                if not bput(out_q, (writes, batch_last_id)):
                    break
                processed_id = batch_last_id
                t_end = time.perf_counter()
                health.heartbeat("serving_loop")
                if n:
                    served += n
                    self.total_count += n
                    last_active = time.monotonic()
                    # latency here is the predict stage alone: decode and
                    # write-back run on their own threads, overlapped —
                    # that overlap is the point of the pipeline
                    self.summary.add_scalar(
                        "Throughput", n / max(t_end - t0, 1e-9),
                        self.total_count)
                    self._record_cycle(n_read, n, t_end - t0)
                if max_records is not None and served >= max_records:
                    break
        finally:
            done.set()
            rt.join(timeout=5.0)
            # the sentinel lands AFTER every enqueued write (FIFO), so
            # the writer flushes (and acks) all handed-off batches first
            try:
                out_q.put(end, timeout=5.0)
            except queue.Full:
                pass
            wt.join(timeout=5.0)
            decode_pool.shutdown(wait=False)
            self._last_id = processed_id
        return served

    # zoolint: hot-path
    def _run_fleet(self, max_records, idle_timeout, health) -> int:
        """Fleet-replica pipeline: claim(lease) + decode + continuous
        batching → predict → write-back + release(done).

        Differences from :meth:`_run_pipelined`, all in service of
        exactly-once across N replicas on one broker:

        - the reader CLAIMS records under ``helper.lease_ms`` instead of
          reading by cursor — other replicas cannot see claimed records,
          and a keepalive thread extends in-flight leases at lease/3 so
          a slow batch (first predict pays the bucketed XLA compile)
          never forfeits mid-flight;
        - decoded records are admitted into per-shape buckets up to
          ``helper.batch_budget_ms`` (:class:`_BucketBatcher`) — a lone
          request is served within the budget, a trickle coalesces into
          one padded predict, a full bucket flushes immediately;
        - the writer RELEASES (``done=True``) each batch's claims only
          after its results are flushed — the claimed-record ack; clean
          shutdown releases leftovers with ``done=False`` so survivors
          re-claim them immediately instead of waiting out the lease.
        """
        in_q: queue.Queue = queue.Queue(maxsize=self._PIPE_DEPTH)
        out_q: queue.Queue = queue.Queue(maxsize=self._PIPE_DEPTH * 2)
        done = threading.Event()
        end = object()  # pipe sentinel
        decode_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="zoo-serving-decode")
        owner = self.owner
        lease_ms = self.helper.lease_ms
        batcher = _BucketBatcher(self.helper.batch_size,
                                 self.helper.batch_budget_ms / 1e3)
        fleet = FleetMetrics()
        inflight_lock = threading.Lock()
        # claimed ids not yet released (reader adds, writer removes,
        # keepalive extends, shutdown requeues)
        inflight: set = set()  # guarded-by: inflight_lock

        def stopped():
            return done.is_set() or self._stop.is_set()

        def bput(q, item) -> bool:
            while not stopped():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def keepalive():
            # extend at lease/3: two missed beats of margin before a
            # survivor may legally take the records over
            period = max(lease_ms / 3000.0, 0.05)
            while not done.wait(period):
                if self._stop.is_set():
                    return
                with inflight_lock:
                    ids = sorted(inflight)
                if not ids:
                    continue
                try:
                    self.db.extend(self.stream, owner, ids, lease_ms)
                except Exception:
                    logger.exception(
                        "serving: lease keepalive failed; continuing")

        def admit(records, now):
            """Hand one claim batch to the batcher.  On ANY failure the
            claimed-but-unadmitted records are dropped from ``inflight``
            (stopping the keepalive renewing their leases forever — the
            wedged-invisible failure mode) and requeued for immediate
            re-claim; if even the requeue fails, the lease simply
            expires to a survivor."""
            with inflight_lock:
                inflight.update(r[0] for r in records)
            admitted: set = set()
            try:
                takeovers = self.db.pop_takeovers(owner)
                if takeovers:
                    # a dead replica's records reclaimed:
                    # the fleet's fault-tolerance event
                    fleet.lease_takeovers.inc(takeovers)
                    self._flight.record(
                        "lease_takeover", owner=owner,
                        records=takeovers)
                decoded = list(decode_pool.map(
                    lambda rf: self._decode_one(rf[0], rf[1]),
                    records))
                bad = [rid for (rid, _), arr
                       in zip(records, decoded) if arr is None]
                if bad:
                    # undecodable/mis-shaped: judged unservable — ack
                    # so no replica loops on them (serial-mode parity)
                    self.db.release(self.stream, owner, bad, done=True)
                    with inflight_lock:
                        inflight.difference_update(bad)
                    admitted.update(bad)  # handled: don't requeue
                for (rid, fields), arr in zip(records, decoded):
                    if arr is not None:
                        batcher.add(rid, fields.get("uri", rid),
                                    arr, now)
                        admitted.add(rid)
            except Exception:
                leftover = [r[0] for r in records
                            if r[0] not in admitted]
                with inflight_lock:
                    inflight.difference_update(leftover)
                try:
                    self.db.release(self.stream, owner, leftover,
                                    done=False)
                except Exception:
                    pass  # broker down: leases expire to survivors
                raise

        def reader():
            health.register("serving_reader", stale_after=120.0)
            depth_refreshed = 0.0
            try:
                while not stopped():
                    try:
                        ratio = self.db.memory_ratio()
                        self.metrics.memory_ratio.set(ratio)
                        if self.trim and ratio >= self.INPUT_THRESHOLD:
                            # zoolint: disable=host-sync -- broker-side host integer, no device involved
                            keep = int(self.db.xlen(self.stream)
                                       * self.CUT_RATIO)
                            self.db.xtrim(self.stream, keep)
                            self.metrics.trims.inc()
                        # block until records OR the nearest partial
                        # bucket's budget, whichever is sooner
                        nd = batcher.next_deadline()
                        block = 100 if nd is None else max(
                            0, min(100, int((nd - time.monotonic()) * 1e3)))  # zoolint: disable=host-sync -- host clock math, no device value
                        records = self.db.claim(
                            self.stream, owner, self.helper.batch_size,
                            lease_ms, block_ms=block)
                        health.heartbeat("serving_reader")
                        now = time.monotonic()
                        if records:
                            admit(records, now)
                            if self.metrics.enabled \
                                    and now - depth_refreshed >= 0.5:
                                # rate-limited: unclaimed() walks the
                                # whole stream (spool listdir / full
                                # scan under the broker lock) — not a
                                # per-batch hot-path cost for a gauge
                                depth_refreshed = now
                                self.metrics.queue_depth.set(
                                    self.db.unclaimed(self.stream))
                        for bucket in batcher.take_ready(time.monotonic()):
                            fleet.batch_flushes.labels(
                                reason=bucket[3]).inc()
                            if not bput(in_q, bucket):
                                return
                    except Exception:
                        # a bad poll/decode must not kill the pipeline
                        logger.exception(
                            "serving: fleet reader failed; continuing")
                        time.sleep(0.05)
            finally:
                health.unregister("serving_reader")
                bput(in_q, end)  # no-op when the main loop already left

        def writer():
            health.register("serving_writer", stale_after=120.0)
            try:
                while True:
                    try:
                        item = out_q.get(timeout=0.5)
                    except queue.Empty:
                        health.heartbeat("serving_writer")
                        continue
                    if item is end:
                        return
                    writes, ids, uris = item
                    try:
                        if writes:
                            self.db.hset_many(writes)
                        # results durable (or the batch judged failed):
                        # NOW the claims end and the records leave the
                        # stream — the exactly-once commit point
                        self.db.release(self.stream, owner, ids,
                                        done=True)
                        if self.serve_log and writes:
                            with open(self.serve_log, "a") as f:
                                # one write() call: O_APPEND keeps
                                # concurrent replicas' lines whole
                                f.write("".join(
                                    f"{owner} {u}\n" for u in uris))
                    except Exception:
                        logger.exception(
                            "serving: write-back failed; continuing")
                    with inflight_lock:
                        inflight.difference_update(ids)
                    health.heartbeat("serving_writer")
            finally:
                health.unregister("serving_writer")

        rt = threading.Thread(target=reader, daemon=True,
                              name="zoo-serving-reader")
        wt = threading.Thread(target=writer, daemon=True,
                              name="zoo-serving-writer")
        kt = threading.Thread(target=keepalive, daemon=True,
                              name="zoo-serving-lease")
        rt.start()
        wt.start()
        kt.start()
        served = 0
        last_active = time.monotonic()
        try:
            while not self._stop.is_set():
                try:
                    item = in_q.get(timeout=0.1)
                except queue.Empty:
                    health.heartbeat("serving_loop")
                    if idle_timeout is not None and \
                            time.monotonic() - last_active > idle_timeout:
                        break
                    continue
                if item is end:
                    break
                ids, uris, arrs, _reason = item
                t0 = time.perf_counter()
                n = 0
                writes = []
                try:
                    with span("zoo.serving.step"):
                        writes = self._predict_groups(
                            self._group_by_shape(uris, arrs))
                    n = len(uris)
                except Exception as e:
                    self._flight.record_exception(e, where="serving.step")
                    logger.exception("serving: batch failed; continuing")
                    writes = []  # failed batch: release done (parity)
                if not bput(out_q, (writes, ids, uris)):
                    break
                t_end = time.perf_counter()
                health.heartbeat("serving_loop")
                if n:
                    served += n
                    self.total_count += n
                    last_active = time.monotonic()
                    self.summary.add_scalar(
                        "Throughput", n / max(t_end - t0, 1e-9),
                        self.total_count)
                    self._record_cycle(len(ids), n, t_end - t0)
                if max_records is not None and served >= max_records:
                    break
        finally:
            done.set()
            rt.join(timeout=5.0)
            # sentinel lands AFTER every enqueued write (FIFO): the
            # writer flushes + releases all handed-off batches first
            try:
                out_q.put(end, timeout=5.0)
            except queue.Full:
                pass
            wt.join(timeout=5.0)
            kt.join(timeout=5.0)
            decode_pool.shutdown(wait=False)
            # requeue every claim this replica still holds (batcher
            # remnants, in_q items, dropped batches): done=False makes
            # them immediately claimable by survivors — a clean exit
            # never makes the fleet wait out a lease
            with inflight_lock:
                leftover = sorted(inflight)
                inflight.clear()
            if leftover:
                try:
                    self.db.release(self.stream, owner, leftover,
                                    done=False)
                except Exception:
                    logger.exception(
                        "serving: shutdown requeue failed; leases will "
                        "expire instead")
        return served

    def start(self, **kwargs) -> "ClusterServing":
        """Run the loop on a daemon thread (embedded serving)."""
        self._thread = threading.Thread(target=self.run, kwargs=kwargs,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
