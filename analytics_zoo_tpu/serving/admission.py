"""Typed front-door admission control for the serving plane (ISSUE 20).

The fleet's old overload valve was the SERVER trimming the stream when
the broker neared memory capacity — which drops records that were
already accepted, silently breaking the client's contract.  This
module moves the shedding to the FRONT DOOR: an
:class:`AdmissionController` watches broker pressure, per-stream
backlog, and the SLO burn headroom (the
:class:`~analytics_zoo_tpu.metrics.slo.SloEngine` multi-window signal
that fires BEFORE the hard violation — BENCH_FED_r15), and publishes a
per-stream verdict hash (``admission:<stream>``) on the broker.
Clients read the verdict at enqueue and raise the typed
:class:`~analytics_zoo_tpu.serving.client.ServingRejected` (with the
retry-after hint sized from the observed drain rate) BEFORE the record
enters the stream.  Admission-guarded servers run with ``trim=False``:
once a record is accepted it is served exactly once, full stop.

Verdicts land the standard three ways: the ``zoo_admission_*`` metric
family, an ``admission`` flight event on every state transition, and a
bounded decision log served in the ``admission`` section of ``/varz``
(rendered by ``tools/metrics_dump.py``).  Gate: ``ZOO_ADMISSION``
(ZooConfig) — the router only attaches a controller when it is on.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..metrics import AdmissionMetrics, get_flight_recorder
from .broker import connect_broker
from .client import ADMISSION_KEY_PREFIX, INPUT_STREAM

__all__ = ["AdmissionController", "varz_doc",
           "DEFAULT_MEMORY_HIGH", "DEFAULT_RESUME_RATIO"]

#: broker memory ratio at which admission sheds — deliberately BELOW
#: the server's trim threshold (``ClusterServing.INPUT_THRESHOLD`` =
#: 0.48): the front door closes before the back-pressure valve would
#: ever need to drop accepted work.
DEFAULT_MEMORY_HIGH = 0.4

#: hysteresis: a shedding stream re-opens only once its backlog has
#: drained below this fraction of the shed threshold — without it the
#: verdict flaps at the boundary and clients see accept/reject noise.
DEFAULT_RESUME_RATIO = 0.5


# ---------------------------------------------------------------------------
# Live-controller registry for /varz (metrics/http.py consults
# sys.modules only — a scrape-only process never imports this module).
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[AdmissionController]" = (  # guarded-by: _active_lock
    weakref.WeakSet())


def varz_doc() -> dict:
    """The ``admission`` section of ``/varz``: every live controller's
    current verdict plus the merged, time-ordered decision log."""
    with _active_lock:
        ctrls = list(_active)
    docs = [c.to_doc() for c in ctrls]
    decisions = sorted((d for doc in docs for d in doc["decisions"]),
                      key=lambda d: d["ts"])
    return {"controllers": docs, "decisions": decisions}


class AdmissionController:
    """Publish accept/shed verdicts for ONE stream.

    ``backlog_limit`` is the total outstanding-record depth (stream
    xlen: unclaimed plus claimed-but-unserved) beyond which new work is
    shed (size it from the fleet's capacity: replicas × service_rate ×
    the SLO's queueing headroom); ``slo_engine`` adds
    the burn-rate trigger — any FIRING alert among ``slo_names``
    (default: all of the engine's alerts) sheds, so the door closes on
    the early-warning signal instead of the violation.  ``admit()`` is
    the in-process front door (counts + raises); cross-process clients
    read the published verdict hash instead."""

    def __init__(self, broker, stream: str = INPUT_STREAM,
                 model: str = "default",
                 backlog_limit: int | None = None,
                 memory_high: float = DEFAULT_MEMORY_HIGH,
                 resume_ratio: float = DEFAULT_RESUME_RATIO,
                 slo_engine=None, slo_names=None,
                 interval: float = 0.25,
                 min_retry_ms: float = 50.0,
                 max_retry_ms: float = 5000.0,
                 registry=None, log_capacity: int = 256):
        if backlog_limit is not None and backlog_limit < 1:
            raise ValueError(
                f"backlog_limit must be >= 1, got {backlog_limit}")
        if not 0.0 < memory_high <= 1.0:
            raise ValueError(
                f"memory_high must be in (0, 1], got {memory_high}")
        if not 0.0 < resume_ratio <= 1.0:
            raise ValueError(
                f"resume_ratio must be in (0, 1], got {resume_ratio}")
        self.db = connect_broker(broker)
        self.stream = str(stream)
        self.model = str(model)
        self.backlog_limit = backlog_limit
        self.memory_high = float(memory_high)
        self.resume_ratio = float(resume_ratio)
        self.slo_engine = slo_engine
        self.slo_names = set(slo_names) if slo_names else None
        self.interval = float(interval)
        self.min_retry_ms = float(min_retry_ms)
        self.max_retry_ms = float(max_retry_ms)
        self.metrics = AdmissionMetrics(registry=registry)
        self._flight = get_flight_recorder()
        self._lock = threading.Lock()
        self._state = "accept"  # guarded-by: _lock
        self._reason = ""  # guarded-by: _lock
        self._retry_after_ms = 0.0  # guarded-by: _lock
        self._decisions: deque = (  # guarded-by: _lock
            deque(maxlen=int(log_capacity)))
        self._prev_backlog: int | None = None  # guarded-by: _lock
        self._prev_t: float | None = None  # guarded-by: _lock
        self._drain_rate = 0.0  # guarded-by: _lock
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop_evt = threading.Event()
        self.metrics.state.labels(model=self.model).set(0)
        with _active_lock:
            _active.add(self)

    # ------------------------------------------------------------------
    # the verdict
    # ------------------------------------------------------------------
    def _verdict_key(self) -> str:
        return ADMISSION_KEY_PREFIX + self.stream

    def evaluate(self) -> dict:
        """One admission tick: read the signals, decide, publish.

        Shed triggers (first match wins the reason): broker memory
        pressure (``broker_pressure``), a firing SLO burn alert
        (``slo_burn``), backlog beyond the limit (``backlog``).  A
        shedding stream re-opens only when EVERY trigger has cleared
        AND the backlog sits below ``resume_ratio × backlog_limit``
        (hysteresis).  Returns the published verdict dict."""
        now = time.monotonic()
        memory_ratio = float(self.db.memory_ratio())
        # TOTAL outstanding accepted work: records stay in the stream
        # until release(done=True), so xlen = unclaimed + claimed-but-
        # unserved.  Gating on unclaimed() alone undercounts — replicas
        # claim a full batch ahead of serving it, and that claimed
        # queue is sojourn time the client still pays.
        backlog = int(self.db.xlen(self.stream))
        with self._lock:
            prev_b, prev_t = self._prev_backlog, self._prev_t
            self._prev_backlog, self._prev_t = backlog, now
            if prev_b is not None and prev_t is not None and now > prev_t:
                drained = (prev_b - backlog) / (now - prev_t)
                if drained > 0:
                    self._drain_rate = drained
            drain_rate = self._drain_rate
            state = self._state
        burn = self._slo_firing()
        reason = ""
        if memory_ratio >= self.memory_high:
            reason = "broker_pressure"
        elif burn:
            reason = f"slo_burn:{burn}"
        elif self.backlog_limit is not None \
                and backlog >= self.backlog_limit:
            reason = "backlog"
        if state == "shed" and not reason:
            # hysteresis: hold the door shut until the backlog is
            # genuinely drained, not merely one record under the limit
            floor = (self.backlog_limit * self.resume_ratio
                     if self.backlog_limit is not None else 0)
            if backlog > floor:
                reason = "draining"
        new_state = "shed" if reason else "accept"
        retry_ms = 0.0
        if new_state == "shed":
            # size the hint from how long the EXCESS backlog takes to
            # drain at the observed rate; bounded so a stalled fleet
            # does not publish infinite waits
            floor = (self.backlog_limit * self.resume_ratio
                     if self.backlog_limit is not None else 0)
            excess = max(backlog - floor, 1)
            if drain_rate > 0:
                retry_ms = excess / drain_rate * 1e3
            else:
                retry_ms = self.max_retry_ms
            retry_ms = min(max(retry_ms, self.min_retry_ms),
                           self.max_retry_ms)
        verdict = {"state": new_state,
                   "retry_after_ms": f"{retry_ms:.1f}",
                   "reason": reason, "ts": f"{time.time():.3f}"}
        self.db.hset(self._verdict_key(), verdict)
        self.metrics.evaluations.inc()
        self.metrics.state.labels(model=self.model).set(
            1 if new_state == "shed" else 0)
        self.metrics.retry_after.labels(model=self.model).set(
            retry_ms / 1e3)
        with self._lock:
            transition = new_state != self._state
            self._state = new_state
            self._reason = reason
            self._retry_after_ms = retry_ms
            if transition:
                self._decisions.append({
                    "ts": time.time(), "model": self.model,
                    "state": new_state, "reason": reason,
                    "retry_after_ms": round(retry_ms, 1),
                    "backlog": backlog,
                    "memory_ratio": round(memory_ratio, 4)})
        if transition:
            self._flight.record(
                "admission", model=self.model, state=new_state,
                reason=reason, retry_after_ms=round(retry_ms, 1),
                backlog=backlog, memory_ratio=round(memory_ratio, 4))
        return verdict

    def _slo_firing(self) -> str:
        """Name of the first firing burn alert this controller watches,
        or empty string."""
        if self.slo_engine is None:
            return ""
        try:
            firing = self.slo_engine.firing()
        except Exception:
            return ""  # a broken engine must not wedge the front door
        names = sorted(str(a.get("slo", "")) for a in firing)
        for name in names:
            if name and (self.slo_names is None
                         or name in self.slo_names):
                return name
        return ""

    # ------------------------------------------------------------------
    # the in-process front door
    # ------------------------------------------------------------------
    def admit(self, uri: str = "") -> None:
        """Accept-or-raise for in-process producers (the bench's load
        generator, an embedded gateway).  Counts every verdict under
        ``zoo_admission_requests_total{model,verdict}``; sheds raise
        :class:`~analytics_zoo_tpu.serving.client.ServingRejected` with
        the current retry-after hint."""
        with self._lock:
            state = self._state
            reason = self._reason
            retry_ms = self._retry_after_ms
        if state == "shed":
            self.metrics.requests.labels(
                model=self.model, verdict="shed").inc()
            from .client import ServingRejected

            raise ServingRejected(uri, retry_after_s=retry_ms / 1e3,
                                  reason=reason)
        self.metrics.requests.labels(
            model=self.model, verdict="accept").inc()

    def state(self) -> str:
        with self._lock:
            return self._state

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "AdmissionController":
        """Tick :meth:`evaluate` on a daemon thread (idempotent)."""
        self._stop_evt.clear()
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-admission")
            t = self._thread
        t.start()
        return self

    def stop(self) -> None:
        """Stop the loop and clear the published verdict (an absent
        hash means unguarded — clients stop paying the verdict read)."""
        self._stop_evt.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        try:
            self.db.delete(self._verdict_key())
        except Exception:
            pass  # broker already gone: nothing to clear

    def _run(self):
        while not self._stop_evt.wait(self.interval):
            try:
                self.evaluate()
            except Exception as e:
                # the front door must never crash the serving plane; a
                # policy bug shows in the flight ring, not an outage
                self._flight.record_exception(e, where="admission")

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def decision_log(self) -> list:
        with self._lock:
            return list(self._decisions)

    def current(self) -> dict:
        with self._lock:
            return {
                "model": self.model, "stream": self.stream,
                "state": self._state, "reason": self._reason,
                "retry_after_ms": round(self._retry_after_ms, 1),
                "backlog_limit": self.backlog_limit,
                "memory_high": self.memory_high,
                "drain_rate": round(self._drain_rate, 3),
            }

    def to_doc(self) -> dict:
        return {"current": self.current(),
                "decisions": self.decision_log()}
