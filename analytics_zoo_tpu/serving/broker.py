"""Stream brokers for Cluster Serving.

Data model mirrors the reference's Redis usage (serving/ClusterServing.scala:
103-139, serving/utils/RedisUtils.scala): an append-only *stream* of
(uri, payload) records, and per-uri *result hashes*.  Three transports:

- :class:`InMemoryBroker` — threading-based, for embedded serving + tests.
- :class:`FileBroker` — a spool directory; atomic-rename appends make it
  safe across processes on one host (the TPU-VM case) with no external
  service.
- :class:`RedisBroker` — the reference transport, gated on ``import redis``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid


class Broker:
    """Minimal stream + hash API (subset of Redis streams).

    The ``claim``/``extend``/``release`` trio is the fleet's
    exactly-once work-claiming protocol (serving/fleet.py): a replica
    CLAIMS records under a lease instead of reading by cursor, so N
    replicas against one stream never double-serve; a replica that dies
    mid-batch simply stops extending, and after ``lease_ms`` its
    claimed-but-unserved records become claimable again (the lease-expiry
    takeover a survivor performs).  ``release(done=True)`` is the
    claimed-record ack; ``done=False`` requeues immediately (clean
    shutdown path — no other replica waits out the lease)."""

    def xadd(self, stream: str, fields: dict) -> str:
        raise NotImplementedError

    def xread(self, stream: str, count: int, last_id: str = "0",
              block_ms: int = 0) -> list:
        """Return up to ``count`` records ``(id, fields)`` with id >
        last_id; optionally block up to ``block_ms``."""
        raise NotImplementedError

    def claim(self, stream: str, owner: str, count: int, lease_ms: int,
              block_ms: int = 0) -> list:
        """Atomically claim up to ``count`` unclaimed (or lease-expired)
        records for ``owner``; returns ``[(id, fields)]``.  Claimed
        records stay in the stream but are invisible to other claimers
        until the lease expires or they are released.  ``block_ms`` > 0
        waits for claimable records (new arrivals OR an expiring
        lease)."""
        raise NotImplementedError

    def extend(self, stream: str, owner: str, ids, lease_ms: int) -> None:
        """Renew ``owner``'s lease on ``ids`` (the mid-batch keepalive —
        a first predict may pay a multi-second XLA compile).  Ids no
        longer owned (expired + taken over, or already released) are
        silently skipped."""
        raise NotImplementedError

    def release(self, stream: str, owner: str, ids,
                done: bool = False) -> None:
        """End ``owner``'s claim on ``ids``.  ``done=True`` acks: the
        records leave the stream (served, or judged unservable).
        ``done=False`` requeues them for immediate re-claim.  Ids not
        currently owned by ``owner`` are silently skipped — a lease that
        expired mid-flight may already belong to a survivor."""
        raise NotImplementedError

    def unclaimed(self, stream: str) -> int:
        """Backlog a new claimer could serve right now: records with no
        live lease.  The fleet autoscaler reads THIS, not ``xlen`` —
        in-flight claimed work is capacity already being used, not
        demand.  Brokers without claim support report ``xlen``."""
        return self.xlen(stream)

    def pop_takeovers(self, owner: str) -> int:
        """Number of lease-EXPIRY takeovers ``owner``'s claims performed
        since the last call (claims of records a dead replica left
        behind).  Read-and-reset; brokers without claim support return
        0."""
        return 0

    def lease_held(self, stream: str) -> bool:
        """True iff ``stream`` is non-empty and every record in it is
        under a LIVE lease — the membership-liveness predicate
        (elastic/membership.py): a worker's single-record member stream
        reports ``True`` while its keepalive extends the claim, and
        flips to ``False`` the instant the lease expires (dead) or the
        record is acked away (clean leave).  Derived from the claim
        protocol, so it holds on all brokers without new state."""
        return self.xlen(stream) > 0 and self.unclaimed(stream) == 0

    def xlen(self, stream: str) -> int:
        raise NotImplementedError

    def xtrim(self, stream: str, maxlen: int) -> None:
        """Drop oldest records beyond ``maxlen`` (backpressure cut,
        ClusterServing.scala:128-134)."""
        raise NotImplementedError

    def ack(self, stream: str, upto_id: str) -> None:
        """Delete consumed records with id <= upto_id (the server acks each
        micro-batch so streams do not grow without bound)."""
        raise NotImplementedError

    def hset(self, key: str, mapping: dict) -> None:
        raise NotImplementedError

    def hset_many(self, items: list) -> None:
        """Write many ``(key, mapping)`` hashes in ONE broker round-trip
        where the transport can (redis pipeline, one lock acquisition);
        this base fallback loops :meth:`hset` so brokers that only
        expose hset stay compatible.  The server writes each
        micro-batch's results through this — never per-record hset."""
        for key, mapping in items:
            self.hset(key, mapping)

    def hgetall(self, key: str) -> dict:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def keys(self, prefix: str) -> list:
        """Hash keys starting with ``prefix`` (the SCAN role needed by
        OutputQueue.dequeue)."""
        raise NotImplementedError

    def memory_ratio(self) -> float:
        """used_memory / maxmemory in [0,1]; brokers that cannot tell
        return 0.0 (no backpressure)."""
        return 0.0

    def close(self) -> None:
        pass


def _new_id() -> str:
    # time-ordered unique id (redis-style "<ms>-<seq>" flavour)
    return "%020d-%s" % (time.time_ns(), uuid.uuid4().hex[:8])


class InMemoryBroker(Broker):
    """All stream/hash/claim state lives under ONE Condition, so every
    blocking read (``xread``/``claim`` with ``block_ms`` > 0) is a
    ``Condition.wait`` woken by ``xadd``/``release`` — an idle fleet
    replica burns no CPU polling (and a claim waiter additionally wakes
    itself at the nearest lease expiry, the dead-replica takeover
    path)."""

    def __init__(self, max_records: int = 1_000_000):
        self._streams: dict[str, list] = {}  # guarded-by: _cv
        self._hashes: dict[str, dict] = {}  # guarded-by: _cv
        # stream -> {rid: (owner, monotonic deadline)} live leases
        self._claims: dict[str, dict] = {}  # guarded-by: _cv
        # owner -> lease-expiry takeovers performed (pop_takeovers)
        self._takeovers: dict[str, int] = {}  # guarded-by: _cv
        self._cv = threading.Condition()
        self._max_records = max_records

    def xadd(self, stream, fields):
        rid = _new_id()
        with self._cv:
            self._streams.setdefault(stream, []).append((rid, dict(fields)))
            self._cv.notify_all()
        return rid

    def xread(self, stream, count, last_id="0", block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        with self._cv:
            while True:
                recs = [r for r in self._streams.get(stream, [])
                        if r[0] > last_id][:count]
                if recs or block_ms <= 0:
                    return recs
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cv.wait(remaining)

    def xlen(self, stream):
        with self._cv:
            return len(self._streams.get(stream, []))

    def xtrim(self, stream, maxlen):
        with self._cv:
            s = self._streams.get(stream, [])
            if len(s) > maxlen:
                dropped = s[:len(s) - maxlen]
                del s[:len(s) - maxlen]
                self._prune_claims_locked(stream, (r[0] for r in dropped))

    def ack(self, stream, upto_id):
        with self._cv:
            s = self._streams.get(stream, [])
            i = 0
            while i < len(s) and s[i][0] <= upto_id:
                i += 1
            acked = s[:i]
            del s[:i]
            self._prune_claims_locked(stream, (r[0] for r in acked))

    def _prune_claims_locked(self, stream, rids):
        """Drop leases for records that left the stream (ack/xtrim)."""
        claims = self._claims.get(stream)
        if claims:
            for rid in rids:
                claims.pop(rid, None)

    # -- exactly-once work claiming (fleet protocol) -------------------

    def claim(self, stream, owner, count, lease_ms, block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        with self._cv:
            while True:
                now = time.monotonic()
                claims = self._claims.setdefault(stream, {})
                out = []
                for rid, fields in self._streams.get(stream, []):
                    cur = claims.get(rid)
                    if cur is not None and cur[1] > now:
                        continue  # live lease held by someone
                    if cur is not None and cur[0] != owner:
                        # expired lease of a (presumed dead) replica
                        self._takeovers[owner] = \
                            self._takeovers.get(owner, 0) + 1
                    claims[rid] = (owner, now + lease_ms / 1000.0)
                    out.append((rid, dict(fields)))
                    if len(out) >= count:
                        break
                if out or block_ms <= 0:
                    return out
                remaining = deadline - now
                if remaining <= 0:
                    return []
                # also wake at the nearest lease expiry: a dead owner's
                # records become claimable without any notify
                expiries = [d for _, d in claims.values() if d > now]
                if expiries:
                    remaining = min(remaining, min(expiries) - now)
                self._cv.wait(max(remaining, 0.0))

    def extend(self, stream, owner, ids, lease_ms):
        with self._cv:
            now = time.monotonic()
            claims = self._claims.get(stream, {})
            for rid in ids:
                cur = claims.get(rid)
                if cur is not None and cur[0] == owner and cur[1] > now:
                    claims[rid] = (owner, now + lease_ms / 1000.0)

    def release(self, stream, owner, ids, done=False):
        ids = set(ids)
        with self._cv:
            claims = self._claims.get(stream, {})
            now = time.monotonic()
            owned = {rid for rid in ids
                     if (c := claims.get(rid)) is not None
                     and c[0] == owner and (done or c[1] > now)}
            # done=True also covers an expired-but-not-yet-taken-over
            # lease: the work WAS completed, the record must go
            for rid in owned:
                claims.pop(rid, None)
            if done and owned:
                s = self._streams.get(stream, [])
                s[:] = [r for r in s if r[0] not in owned]
            if owned and not done:
                self._cv.notify_all()  # requeued: wake claim waiters

    def unclaimed(self, stream):
        with self._cv:
            now = time.monotonic()
            claims = self._claims.get(stream, {})
            return sum(
                1 for rid, _ in self._streams.get(stream, [])
                if (c := claims.get(rid)) is None or c[1] <= now)

    def pop_takeovers(self, owner):
        with self._cv:
            return self._takeovers.pop(owner, 0)

    def hset(self, key, mapping):
        with self._cv:
            self._hashes.setdefault(key, {}).update(mapping)
            self._cv.notify_all()

    def hset_many(self, items):
        # one lock acquisition + one wakeup for the whole micro-batch
        with self._cv:
            for key, mapping in items:
                self._hashes.setdefault(key, {}).update(mapping)
            self._cv.notify_all()

    def hgetall(self, key):
        with self._cv:
            return dict(self._hashes.get(key, {}))

    def delete(self, key):
        with self._cv:
            self._hashes.pop(key, None)

    def keys(self, prefix):
        with self._cv:
            return [k for k in self._hashes if k.startswith(prefix)]

    def memory_ratio(self):
        n = sum(len(s) for s in self._streams.values())
        return min(1.0, n / self._max_records)


class FileBroker(Broker):
    """Spool-directory broker.

    Streams live under ``<root>/stream-<name>/<id>.json``; appends write a
    temp file then ``os.rename`` (atomic on POSIX), so multiple client
    processes and one server process interoperate without locks.  Result
    hashes are single json files under ``<root>/hash/``.
    """

    def __init__(self, root: str, max_bytes: int = 1 << 30):
        self.root = root
        self.max_bytes = int(max_bytes)
        # lease-expiry takeovers THIS instance performed, by owner
        # (one broker instance per replica process — no lock needed)
        self._takeovers: dict[str, int] = {}
        os.makedirs(os.path.join(root, "hash"), exist_ok=True)

    def _sdir(self, stream):
        d = os.path.join(self.root, "stream-" + stream)
        os.makedirs(d, exist_ok=True)
        return d

    def _hpath(self, key):
        return os.path.join(self.root, "hash", key.replace("/", "_") + ".json")

    def xadd(self, stream, fields):
        rid = _new_id()
        d = self._sdir(stream)
        tmp = os.path.join(d, ".tmp-" + rid)
        with open(tmp, "w") as f:
            json.dump(fields, f)
        os.rename(tmp, os.path.join(d, rid + ".json"))
        return rid

    def _ids(self, stream):
        d = self._sdir(stream)
        return sorted(n[:-5] for n in os.listdir(d)
                      if n.endswith(".json") and not n.startswith("."))

    def xread(self, stream, count, last_id="0", block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        d = self._sdir(stream)
        while True:
            out = []
            for rid in self._ids(stream):
                if rid <= last_id:
                    continue
                try:
                    with open(os.path.join(d, rid + ".json")) as f:
                        out.append((rid, json.load(f)))
                except (OSError, json.JSONDecodeError):
                    continue  # trimmed or mid-write by a racing producer
                if len(out) >= count:
                    break
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(0.01)

    def xlen(self, stream):
        return len(self._ids(stream))

    def _remove_record(self, stream, rid):
        d = self._sdir(stream)
        for p in (os.path.join(d, rid + ".json"),
                  self._cpath(stream, rid)):  # no orphan claim dotfiles
            try:
                os.remove(p)
            except OSError:
                pass

    def xtrim(self, stream, maxlen):
        ids = self._ids(stream)
        for rid in ids[:max(0, len(ids) - maxlen)]:
            self._remove_record(stream, rid)

    def ack(self, stream, upto_id):
        for rid in self._ids(stream):
            if rid > upto_id:
                break
            self._remove_record(stream, rid)

    # -- exactly-once work claiming (fleet protocol) -------------------
    #
    # A claim is a dotfile next to the record (".c-<rid>.json" — hidden
    # from _ids) holding {"owner", "exp" (wall-clock lease deadline)}.
    # Claim files are born ATOMICALLY WITH FULL CONTENT via os.link from
    # a private temp file — link(2) fails with EEXIST when the path is
    # taken, which is the cross-process compare-and-claim: exactly one
    # replica wins a fresh record, and no reader ever sees a half-written
    # claim.  Lease-expiry takeover renames the expired claim to a
    # private tombstone first (again: exactly one renamer of that path
    # wins), verifies the tombstone is the expired claim it read, then
    # links its own claim in.  Two survivors reclaiming the same dead
    # replica's record therefore resolve atomically; only a >2-way
    # reclaim storm interleaved within the same few microseconds can
    # degrade to at-least-once (results are idempotent hset writes, and
    # the Redis transport gets true single-server atomicity).

    def _cpath(self, stream, rid):
        return os.path.join(self._sdir(stream), ".c-" + rid + ".json")

    @staticmethod
    def _read_claim(cpath):
        """(owner, exp) of a claim file, or None when absent/unreadable
        (unreadable cannot happen via the link protocol — treated as
        absent so a manually-corrupted claim does not wedge a record)."""
        try:
            with open(cpath) as f:
                doc = json.load(f)
            return str(doc.get("owner", "")), float(doc.get("exp", 0.0))
        except (OSError, ValueError):
            return None

    def _link_claim(self, cpath, owner, lease_ms) -> bool:
        """Atomically create ``cpath`` with a fresh lease; False when the
        path is already claimed."""
        tmp = cpath + ".tmp-" + uuid.uuid4().hex[:8]
        with open(tmp, "w") as f:
            json.dump({"owner": owner,
                       "exp": time.time() + lease_ms / 1000.0}, f)
        try:
            os.link(tmp, cpath)
            return True
        except OSError:
            return False
        finally:
            os.remove(tmp)

    def _try_claim(self, stream, rid, owner, lease_ms):
        """Claim one record; returns (rid, fields) or None (lost a race /
        live lease / record vanished).  Second element of the return is
        via self._claim_takeovers bookkeeping."""
        cpath = self._cpath(stream, rid)
        cur = self._read_claim(cpath)
        if cur is None:
            if not self._link_claim(cpath, owner, lease_ms):
                return None
        elif cur[1] <= time.time():
            # expired lease: tombstone-rename is the atomic takeover
            tomb = cpath + ".to-" + uuid.uuid4().hex[:8]
            try:
                os.rename(cpath, tomb)
            except OSError:
                return None  # another claimer already took it
            grabbed = self._read_claim(tomb)
            if grabbed is not None and grabbed != cur:
                # raced past a fresh re-claim: restore it (atomic —
                # link fails if yet another claim landed meanwhile)
                try:
                    os.link(tomb, cpath)
                except OSError:
                    pass
                os.remove(tomb)
                return None
            ok = self._link_claim(cpath, owner, lease_ms)
            try:
                os.remove(tomb)
            except OSError:
                pass
            if not ok:
                return None
            if cur[0] != owner:
                self._takeovers[owner] = self._takeovers.get(owner, 0) + 1
        else:
            return None  # live lease
        # claimed — but the record may have been trimmed/acked meanwhile
        try:
            with open(os.path.join(self._sdir(stream),
                                   rid + ".json")) as f:
                return rid, json.load(f)
        except (OSError, json.JSONDecodeError):
            try:
                os.remove(cpath)
            except OSError:
                pass
            return None

    def claim(self, stream, owner, count, lease_ms, block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        while True:
            out = []
            for rid in self._ids(stream):
                got = self._try_claim(stream, rid, owner, lease_ms)
                if got is None:
                    continue
                out.append(got)
                if len(out) >= count:
                    break
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(0.01)  # cross-process spool: poll is the only wake

    def _take_own_claim(self, cpath, owner):
        """Atomically rename ``owner``'s claim off ``cpath``; returns the
        tombstone path, or None when the path is gone or holds someone
        else's claim (which is restored untouched).  rename(2) is the
        exclusivity: a takeover that raced past our last read cannot be
        clobbered, because only one renamer of the path wins."""
        tomb = cpath + ".ex-" + uuid.uuid4().hex[:8]
        try:
            os.rename(cpath, tomb)
        except OSError:
            return None  # a takeover owns the path right now
        grabbed = self._read_claim(tomb)
        if grabbed is None or grabbed[0] != owner:
            # raced: a survivor's fresh claim was at the path — restore
            # it (link fails only if yet another claim landed meanwhile)
            try:
                os.link(tomb, cpath)
            except OSError:
                pass
            try:
                os.remove(tomb)
            except OSError:
                pass
            return None
        return tomb

    def extend(self, stream, owner, ids, lease_ms):
        for rid in ids:
            cpath = self._cpath(stream, rid)
            cur = self._read_claim(cpath)
            now = time.time()
            # Renew via atomic rename-REPLACE (the path is never absent,
            # so a concurrent claimer can never read 'unclaimed' off a
            # live lease), but only while a 50ms stall guard remains
            # before expiry: a takeover is only legal AFTER expiry, so
            # the replace can only clobber a survivor's claim if this
            # process stalls longer than the guard between this check
            # and the rename — the same pause class the lease protocol
            # already concedes to at-least-once (results are idempotent
            # hset writes).  A lease inside the guard is left to ride
            # out (the keepalive beats at lease/3, far from the guard).
            if cur is None or cur[0] != owner or cur[1] - now <= 0.05:
                continue  # no longer (safely) ours — let the lease ride
            tmp = cpath + ".tmp-" + uuid.uuid4().hex[:8]
            with open(tmp, "w") as f:
                json.dump({"owner": owner,
                           "exp": now + lease_ms / 1000.0}, f)
            os.rename(tmp, cpath)

    def release(self, stream, owner, ids, done=False):
        d = self._sdir(stream)
        for rid in ids:
            cpath = self._cpath(stream, rid)
            cur = self._read_claim(cpath)
            if cur is None or cur[0] != owner:
                continue
            if done:
                # record first, claim second: a crash in between leaves
                # an orphan claim on a gone record, which _try_claim
                # already cleans up — never the reverse (an unclaimed
                # but served record would be re-served).  A takeover
                # racing the claim removal is harmless here: the record
                # is gone, so the survivor's claim is an orphan either
                # way.
                try:
                    os.remove(os.path.join(d, rid + ".json"))
                except OSError:
                    pass
                try:
                    os.remove(cpath)
                except OSError:
                    pass
            else:
                # requeue: take the path atomically first — deleting
                # blind could remove a survivor's just-taken-over claim
                # and hand the record to a THIRD replica mid-serve
                tomb = self._take_own_claim(cpath, owner)
                if tomb is not None:
                    try:
                        os.remove(tomb)
                    except OSError:
                        pass

    def unclaimed(self, stream):
        # ONE listdir, then read only the claim dotfiles actually
        # present (≈ replicas × batch_size) — NOT one failed open per
        # backlog record; a deep backlog is exactly when the autoscaler
        # polls this and must not slow down
        now = time.time()
        try:
            names = os.listdir(self._sdir(stream))
        except OSError:
            return 0
        recs = {n[:-5] for n in names
                if n.endswith(".json") and not n.startswith(".")}
        live = 0
        for n in names:
            if not (n.startswith(".c-") and n.endswith(".json")):
                continue  # tombstones/tmps never end in .json
            if n[3:-5] not in recs:
                continue  # orphan claim on a trimmed/acked record
            cur = self._read_claim(
                os.path.join(self._sdir(stream), n))
            if cur is not None and cur[1] > now:
                live += 1
        return len(recs) - live

    def pop_takeovers(self, owner):
        return self._takeovers.pop(owner, 0)

    _RATIO_TTL = 0.5  # seconds between spool re-scans

    def memory_ratio(self):
        """Spool bytes / max_bytes — the one broker that can actually fill a
        disk must report pressure so the server's xtrim backpressure path
        (server.py; semantics ClusterServing.scala:128-134) engages.

        The scan walks every spool file, and OTHER processes append to the
        spool (clients xadd from their own FileBroker instances), so an
        in-process byte counter can't work; instead the scan result is
        cached for ``_RATIO_TTL`` seconds to bound syscall cost per
        serving step."""
        now = time.monotonic()
        cached = getattr(self, "_ratio_cache", None)
        if cached is not None and now - cached[0] < self._RATIO_TTL:
            return cached[1]
        used = 0
        try:
            entries = os.listdir(self.root)
        except OSError:
            return 0.0
        for name in entries:
            if not name.startswith("stream-"):
                continue
            d = os.path.join(self.root, name)
            try:
                with os.scandir(d) as it:
                    for e in it:
                        try:
                            used += e.stat().st_size
                        except OSError:
                            pass
            except OSError:
                pass
        ratio = min(1.0, used / max(self.max_bytes, 1))
        self._ratio_cache = (now, ratio)
        return ratio

    def hset(self, key, mapping):
        p = self._hpath(key)
        cur = self.hgetall(key)
        cur.update(mapping)
        tmp = p + ".tmp-" + uuid.uuid4().hex[:8]
        with open(tmp, "w") as f:
            json.dump(cur, f)
        os.rename(tmp, p)

    def hgetall(self, key):
        try:
            with open(self._hpath(key)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}

    def delete(self, key):
        try:
            os.remove(self._hpath(key))
        except OSError:
            pass

    def keys(self, prefix):
        # filenames are the mangled keys ("/" -> "_"); the mangle is
        # idempotent, so returned keys round-trip through hgetall/delete
        # (uris containing "/" come back with "_")
        d = os.path.join(self.root, "hash")
        pfx = prefix.replace("/", "_")
        try:
            names = os.listdir(d)
        except OSError:
            return []
        return [n[:-5] for n in names
                if n.endswith(".json") and n.startswith(pfx)]


class RedisBroker(Broker):
    """The reference transport (Jedis in ClusterServing.scala:119).  Gated
    on the ``redis`` package; raises ImportError with guidance if absent."""

    def __init__(self, host: str = "localhost", port: int = 6379):
        try:
            import redis
        except ImportError as e:  # pragma: no cover - redis not in image
            raise ImportError(
                "RedisBroker requires the 'redis' package; use "
                "FileBroker/InMemoryBroker or install redis-py") from e
        self._r = redis.Redis(host=host, port=port, decode_responses=True)
        self._takeovers: dict[str, int] = {}
        # last lease claim() was called with: unclaimed() needs it to
        # tell expired (claimable) PEL entries from live in-flight ones
        self._last_lease_ms: int | None = None

    def xadd(self, stream, fields):  # pragma: no cover - needs server
        return self._r.xadd(stream, fields)

    def xread(self, stream, count, last_id="0", block_ms=0):
        # pragma: no cover - needs server
        res = self._r.xread({stream: last_id}, count=count,
                            block=block_ms or None)
        return [(rid, fields) for _, recs in res for rid, fields in recs]

    def xlen(self, stream):  # pragma: no cover
        return self._r.xlen(stream)

    def xtrim(self, stream, maxlen):  # pragma: no cover
        self._r.xtrim(stream, maxlen=maxlen, approximate=True)

    def ack(self, stream, upto_id):  # pragma: no cover
        # XTRIM MINID evicts ids strictly below minid, so pass the successor
        # of upto_id (redis ids are "<ms>-<seq>")
        ms, _, seq = upto_id.partition("-")
        succ = f"{ms}-{int(seq or 0) + 1}"
        self._r.xtrim(stream, minid=succ, approximate=False)

    # -- exactly-once work claiming: the Redis-native mapping is stream
    # consumer groups — XREADGROUP hands each entry to ONE consumer,
    # XAUTOCLAIM reassigns entries idle past the lease (the dead-replica
    # takeover), XACK+XDEL is release(done=True).
    _GROUP = "zoo-fleet"

    def _ensure_group(self, stream):  # pragma: no cover - needs server
        try:
            self._r.xgroup_create(stream, self._GROUP, id="0",
                                  mkstream=True)
        except Exception:
            pass  # BUSYGROUP: already exists

    def claim(self, stream, owner, count, lease_ms,
              block_ms=0):  # pragma: no cover - needs server
        self._ensure_group(stream)
        self._last_lease_ms = int(lease_ms)
        out = []
        # 1) reclaim entries whose consumer went idle past the lease
        try:
            res = self._r.xautoclaim(stream, self._GROUP, owner,
                                     min_idle_time=int(lease_ms),
                                     count=count)
            reclaimed = res[1] if isinstance(res, (list, tuple)) else []
        except Exception:
            reclaimed = []
        for rid, fields in reclaimed:
            out.append((rid, fields))
            self._takeovers[owner] = self._takeovers.get(owner, 0) + 1
        # 2) then fresh, never-delivered entries; never block when the
        # reclaim already produced records — a takeover drain must not
        # pay block_ms per cycle on top of the lease it waited out
        need = count - len(out)
        if need > 0:
            res = self._r.xreadgroup(self._GROUP, owner, {stream: ">"},
                                     count=need,
                                     block=(block_ms or None)
                                     if not out else None)
            for _, recs in res or []:
                out.extend(recs)
        return out

    def extend(self, stream, owner, ids,
               lease_ms):  # pragma: no cover - needs server
        # XCLAIM justid resets the idle clock without changing ownership
        if ids:
            try:
                self._r.xclaim(stream, self._GROUP, owner, min_idle_time=0,
                               message_ids=list(ids), justid=True)
            except Exception:
                pass

    def release(self, stream, owner, ids,
                done=False):  # pragma: no cover - needs server
        ids = list(ids)
        if not ids:
            return
        if done:
            self._r.xack(stream, self._GROUP, *ids)
            self._r.xdel(stream, *ids)
        # done=False: leave the entries in the group's PEL — XAUTOCLAIM
        # hands them to a survivor once the lease idles out.  (XACK here
        # would be WRONG: acked entries never re-deliver to the group.)
        # Requeue latency is therefore one lease on this transport.

    def unclaimed(self, stream):  # pragma: no cover - needs server
        try:
            info = self._r.xpending(stream, self._GROUP)
            pending = int(info.get("pending", 0)) if isinstance(info, dict) \
                else 0
            if pending and self._last_lease_ms:
                # PEL entries idle past the lease are a dead replica's
                # forfeited work — claimable demand the autoscaler must
                # see, NOT in-flight capacity; don't subtract them
                try:
                    expired = len(self._r.xpending_range(
                        stream, self._GROUP, min="-", max="+",
                        count=pending, idle=self._last_lease_ms))
                    pending -= min(pending, expired)
                except Exception:
                    pass  # older server/client without IDLE filtering
        except Exception:
            pending = 0
        return max(0, self.xlen(stream) - pending)

    def pop_takeovers(self, owner):  # pragma: no cover - needs server
        return self._takeovers.pop(owner, 0)

    def hset(self, key, mapping):  # pragma: no cover
        self._r.hset(key, mapping=mapping)

    def hset_many(self, items):  # pragma: no cover
        # MULTI-free pipeline: one network round-trip per micro-batch
        # (the reference scripts its write-back the same way,
        # RedisUtils.scala)
        p = self._r.pipeline(transaction=False)
        for key, mapping in items:
            p.hset(key, mapping=mapping)
        p.execute()

    def hgetall(self, key):  # pragma: no cover
        return self._r.hgetall(key)

    def delete(self, key):  # pragma: no cover
        self._r.delete(key)

    def keys(self, prefix):  # pragma: no cover
        # _type="hash": a shared db may hold non-hash keys under the same
        # prefix; hgetall on those would raise WRONGTYPE mid-dequeue
        return list(self._r.scan_iter(match=prefix + "*", _type="hash"))

    def memory_ratio(self):  # pragma: no cover
        info = self._r.info("memory")
        mx = int(info.get("maxmemory", 0))
        return (int(info["used_memory"]) / mx) if mx else 0.0


def connect_broker(spec) -> Broker:
    """Build a broker from a spec: a Broker instance (returned as-is), a
    ``dir:`` / plain path (FileBroker), ``memory``, or ``host:port``
    (RedisBroker)."""
    if isinstance(spec, Broker):
        return spec
    if spec is None or spec == "memory":
        return InMemoryBroker()
    spec = str(spec)
    if spec.startswith("dir:"):
        return FileBroker(spec[4:])
    if ":" in spec and not os.sep in spec:
        host, port = spec.rsplit(":", 1)
        return RedisBroker(host, int(port))
    return FileBroker(spec)
