"""Stream brokers for Cluster Serving.

Data model mirrors the reference's Redis usage (serving/ClusterServing.scala:
103-139, serving/utils/RedisUtils.scala): an append-only *stream* of
(uri, payload) records, and per-uri *result hashes*.  Three transports:

- :class:`InMemoryBroker` — threading-based, for embedded serving + tests.
- :class:`FileBroker` — a spool directory; atomic-rename appends make it
  safe across processes on one host (the TPU-VM case) with no external
  service.
- :class:`RedisBroker` — the reference transport, gated on ``import redis``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid


class Broker:
    """Minimal stream + hash API (subset of Redis streams)."""

    def xadd(self, stream: str, fields: dict) -> str:
        raise NotImplementedError

    def xread(self, stream: str, count: int, last_id: str = "0",
              block_ms: int = 0) -> list:
        """Return up to ``count`` records ``(id, fields)`` with id >
        last_id; optionally block up to ``block_ms``."""
        raise NotImplementedError

    def xlen(self, stream: str) -> int:
        raise NotImplementedError

    def xtrim(self, stream: str, maxlen: int) -> None:
        """Drop oldest records beyond ``maxlen`` (backpressure cut,
        ClusterServing.scala:128-134)."""
        raise NotImplementedError

    def ack(self, stream: str, upto_id: str) -> None:
        """Delete consumed records with id <= upto_id (the server acks each
        micro-batch so streams do not grow without bound)."""
        raise NotImplementedError

    def hset(self, key: str, mapping: dict) -> None:
        raise NotImplementedError

    def hset_many(self, items: list) -> None:
        """Write many ``(key, mapping)`` hashes in ONE broker round-trip
        where the transport can (redis pipeline, one lock acquisition);
        this base fallback loops :meth:`hset` so brokers that only
        expose hset stay compatible.  The server writes each
        micro-batch's results through this — never per-record hset."""
        for key, mapping in items:
            self.hset(key, mapping)

    def hgetall(self, key: str) -> dict:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def keys(self, prefix: str) -> list:
        """Hash keys starting with ``prefix`` (the SCAN role needed by
        OutputQueue.dequeue)."""
        raise NotImplementedError

    def memory_ratio(self) -> float:
        """used_memory / maxmemory in [0,1]; brokers that cannot tell
        return 0.0 (no backpressure)."""
        return 0.0

    def close(self) -> None:
        pass


def _new_id() -> str:
    # time-ordered unique id (redis-style "<ms>-<seq>" flavour)
    return "%020d-%s" % (time.time_ns(), uuid.uuid4().hex[:8])


class InMemoryBroker(Broker):
    def __init__(self, max_records: int = 1_000_000):
        self._streams: dict[str, list] = {}  # guarded-by: _cv
        self._hashes: dict[str, dict] = {}  # guarded-by: _cv
        self._cv = threading.Condition()
        self._max_records = max_records

    def xadd(self, stream, fields):
        rid = _new_id()
        with self._cv:
            self._streams.setdefault(stream, []).append((rid, dict(fields)))
            self._cv.notify_all()
        return rid

    def xread(self, stream, count, last_id="0", block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        with self._cv:
            while True:
                recs = [r for r in self._streams.get(stream, [])
                        if r[0] > last_id][:count]
                if recs or block_ms <= 0:
                    return recs
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cv.wait(remaining)

    def xlen(self, stream):
        with self._cv:
            return len(self._streams.get(stream, []))

    def xtrim(self, stream, maxlen):
        with self._cv:
            s = self._streams.get(stream, [])
            if len(s) > maxlen:
                del s[:len(s) - maxlen]

    def ack(self, stream, upto_id):
        with self._cv:
            s = self._streams.get(stream, [])
            i = 0
            while i < len(s) and s[i][0] <= upto_id:
                i += 1
            del s[:i]

    def hset(self, key, mapping):
        with self._cv:
            self._hashes.setdefault(key, {}).update(mapping)
            self._cv.notify_all()

    def hset_many(self, items):
        # one lock acquisition + one wakeup for the whole micro-batch
        with self._cv:
            for key, mapping in items:
                self._hashes.setdefault(key, {}).update(mapping)
            self._cv.notify_all()

    def hgetall(self, key):
        with self._cv:
            return dict(self._hashes.get(key, {}))

    def delete(self, key):
        with self._cv:
            self._hashes.pop(key, None)

    def keys(self, prefix):
        with self._cv:
            return [k for k in self._hashes if k.startswith(prefix)]

    def memory_ratio(self):
        n = sum(len(s) for s in self._streams.values())
        return min(1.0, n / self._max_records)


class FileBroker(Broker):
    """Spool-directory broker.

    Streams live under ``<root>/stream-<name>/<id>.json``; appends write a
    temp file then ``os.rename`` (atomic on POSIX), so multiple client
    processes and one server process interoperate without locks.  Result
    hashes are single json files under ``<root>/hash/``.
    """

    def __init__(self, root: str, max_bytes: int = 1 << 30):
        self.root = root
        self.max_bytes = int(max_bytes)
        os.makedirs(os.path.join(root, "hash"), exist_ok=True)

    def _sdir(self, stream):
        d = os.path.join(self.root, "stream-" + stream)
        os.makedirs(d, exist_ok=True)
        return d

    def _hpath(self, key):
        return os.path.join(self.root, "hash", key.replace("/", "_") + ".json")

    def xadd(self, stream, fields):
        rid = _new_id()
        d = self._sdir(stream)
        tmp = os.path.join(d, ".tmp-" + rid)
        with open(tmp, "w") as f:
            json.dump(fields, f)
        os.rename(tmp, os.path.join(d, rid + ".json"))
        return rid

    def _ids(self, stream):
        d = self._sdir(stream)
        return sorted(n[:-5] for n in os.listdir(d)
                      if n.endswith(".json") and not n.startswith("."))

    def xread(self, stream, count, last_id="0", block_ms=0):
        deadline = time.monotonic() + block_ms / 1000.0
        d = self._sdir(stream)
        while True:
            out = []
            for rid in self._ids(stream):
                if rid <= last_id:
                    continue
                try:
                    with open(os.path.join(d, rid + ".json")) as f:
                        out.append((rid, json.load(f)))
                except (OSError, json.JSONDecodeError):
                    continue  # trimmed or mid-write by a racing producer
                if len(out) >= count:
                    break
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(0.01)

    def xlen(self, stream):
        return len(self._ids(stream))

    def xtrim(self, stream, maxlen):
        ids = self._ids(stream)
        d = self._sdir(stream)
        for rid in ids[:max(0, len(ids) - maxlen)]:
            try:
                os.remove(os.path.join(d, rid + ".json"))
            except OSError:
                pass

    def ack(self, stream, upto_id):
        d = self._sdir(stream)
        for rid in self._ids(stream):
            if rid > upto_id:
                break
            try:
                os.remove(os.path.join(d, rid + ".json"))
            except OSError:
                pass

    _RATIO_TTL = 0.5  # seconds between spool re-scans

    def memory_ratio(self):
        """Spool bytes / max_bytes — the one broker that can actually fill a
        disk must report pressure so the server's xtrim backpressure path
        (server.py; semantics ClusterServing.scala:128-134) engages.

        The scan walks every spool file, and OTHER processes append to the
        spool (clients xadd from their own FileBroker instances), so an
        in-process byte counter can't work; instead the scan result is
        cached for ``_RATIO_TTL`` seconds to bound syscall cost per
        serving step."""
        now = time.monotonic()
        cached = getattr(self, "_ratio_cache", None)
        if cached is not None and now - cached[0] < self._RATIO_TTL:
            return cached[1]
        used = 0
        try:
            entries = os.listdir(self.root)
        except OSError:
            return 0.0
        for name in entries:
            if not name.startswith("stream-"):
                continue
            d = os.path.join(self.root, name)
            try:
                with os.scandir(d) as it:
                    for e in it:
                        try:
                            used += e.stat().st_size
                        except OSError:
                            pass
            except OSError:
                pass
        ratio = min(1.0, used / max(self.max_bytes, 1))
        self._ratio_cache = (now, ratio)
        return ratio

    def hset(self, key, mapping):
        p = self._hpath(key)
        cur = self.hgetall(key)
        cur.update(mapping)
        tmp = p + ".tmp-" + uuid.uuid4().hex[:8]
        with open(tmp, "w") as f:
            json.dump(cur, f)
        os.rename(tmp, p)

    def hgetall(self, key):
        try:
            with open(self._hpath(key)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}

    def delete(self, key):
        try:
            os.remove(self._hpath(key))
        except OSError:
            pass

    def keys(self, prefix):
        # filenames are the mangled keys ("/" -> "_"); the mangle is
        # idempotent, so returned keys round-trip through hgetall/delete
        # (uris containing "/" come back with "_")
        d = os.path.join(self.root, "hash")
        pfx = prefix.replace("/", "_")
        try:
            names = os.listdir(d)
        except OSError:
            return []
        return [n[:-5] for n in names
                if n.endswith(".json") and n.startswith(pfx)]


class RedisBroker(Broker):
    """The reference transport (Jedis in ClusterServing.scala:119).  Gated
    on the ``redis`` package; raises ImportError with guidance if absent."""

    def __init__(self, host: str = "localhost", port: int = 6379):
        try:
            import redis
        except ImportError as e:  # pragma: no cover - redis not in image
            raise ImportError(
                "RedisBroker requires the 'redis' package; use "
                "FileBroker/InMemoryBroker or install redis-py") from e
        self._r = redis.Redis(host=host, port=port, decode_responses=True)

    def xadd(self, stream, fields):  # pragma: no cover - needs server
        return self._r.xadd(stream, fields)

    def xread(self, stream, count, last_id="0", block_ms=0):
        # pragma: no cover - needs server
        res = self._r.xread({stream: last_id}, count=count,
                            block=block_ms or None)
        return [(rid, fields) for _, recs in res for rid, fields in recs]

    def xlen(self, stream):  # pragma: no cover
        return self._r.xlen(stream)

    def xtrim(self, stream, maxlen):  # pragma: no cover
        self._r.xtrim(stream, maxlen=maxlen, approximate=True)

    def ack(self, stream, upto_id):  # pragma: no cover
        # XTRIM MINID evicts ids strictly below minid, so pass the successor
        # of upto_id (redis ids are "<ms>-<seq>")
        ms, _, seq = upto_id.partition("-")
        succ = f"{ms}-{int(seq or 0) + 1}"
        self._r.xtrim(stream, minid=succ, approximate=False)

    def hset(self, key, mapping):  # pragma: no cover
        self._r.hset(key, mapping=mapping)

    def hset_many(self, items):  # pragma: no cover
        # MULTI-free pipeline: one network round-trip per micro-batch
        # (the reference scripts its write-back the same way,
        # RedisUtils.scala)
        p = self._r.pipeline(transaction=False)
        for key, mapping in items:
            p.hset(key, mapping=mapping)
        p.execute()

    def hgetall(self, key):  # pragma: no cover
        return self._r.hgetall(key)

    def delete(self, key):  # pragma: no cover
        self._r.delete(key)

    def keys(self, prefix):  # pragma: no cover
        # _type="hash": a shared db may hold non-hash keys under the same
        # prefix; hgetall on those would raise WRONGTYPE mid-dequeue
        return list(self._r.scan_iter(match=prefix + "*", _type="hash"))

    def memory_ratio(self):  # pragma: no cover
        info = self._r.info("memory")
        mx = int(info.get("maxmemory", 0))
        return (int(info["used_memory"]) / mx) if mx else 0.0


def connect_broker(spec) -> Broker:
    """Build a broker from a spec: a Broker instance (returned as-is), a
    ``dir:`` / plain path (FileBroker), ``memory``, or ``host:port``
    (RedisBroker)."""
    if isinstance(spec, Broker):
        return spec
    if spec is None or spec == "memory":
        return InMemoryBroker()
    spec = str(spec)
    if spec.startswith("dir:"):
        return FileBroker(spec[4:])
    if ":" in spec and not os.sep in spec:
        host, port = spec.rsplit(":", 1)
        return RedisBroker(host, int(port))
    return FileBroker(spec)
