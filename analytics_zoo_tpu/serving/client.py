"""Cluster Serving client (reference pyzoo/zoo/serving/client.py).

``InputQueue.enqueue_image`` pushes (uri, tensor) onto the input stream;
``OutputQueue.query/dequeue`` reads prediction results back.  Tensors travel
base64-encoded (npy bytes) like the reference's base64 JPEG strings
(client.py:122 ``base64_encode_image``), but dtype/shape-preserving.
"""

from __future__ import annotations

import base64
import io
import time

import numpy as np

from .broker import connect_broker

INPUT_STREAM = "image_stream"  # reference stream key, ClusterServing.scala:108
RESULT_PREFIX = "result:"
# Front-door admission verdict hash per stream (serving/admission.py):
# {"state": "accept"|"shed", "retry_after_ms", "reason", "ts"}.  The
# client reads it at enqueue; an absent hash means no admission
# controller guards the stream and every enqueue is accepted.
ADMISSION_KEY_PREFIX = "admission:"


def model_stream(model: str) -> str:
    """Input stream for one routed model (serving/router.py): the
    single-tenant default stream stays ``image_stream`` so existing
    clients are untouched; routed models get ``model_stream:<name>``."""
    return f"model_stream:{model}"


class ServingRejected(RuntimeError):
    """The admission controller shed this enqueue at the front door.

    Typed like :class:`ServingTimeout`: carries the ``uri``, the
    ``retry_after_s`` hint the verdict published (obey it — the
    controller sized it from the backlog drain rate), and the
    ``reason`` (broker_pressure / slo_burn / backlog).  Raised BEFORE
    the record enters the stream — a rejected request was never
    accepted, so the exactly-once guarantee over accepted work is
    undiluted."""

    def __init__(self, uri: str, retry_after_s: float, reason: str = ""):
        super().__init__(
            f"enqueue of {uri!r} shed by admission control"
            f"{f' ({reason})' if reason else ''}; retry after "
            f"{retry_after_s:.2f}s")
        self.uri = uri
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


class ServingTimeout(TimeoutError):
    """A result did not arrive within the polling deadline.

    Carries the ``uri`` and the ``timeout`` that elapsed, so callers can
    requeue or alert on the specific lost record instead of parsing a
    message string."""

    def __init__(self, uri: str, timeout: float):
        super().__init__(
            f"no result for {uri!r} within {timeout:.1f}s — the record "
            "was trimmed under backpressure, dropped as undecodable, or "
            "the serving fleet is down (check /healthz and "
            "zoo_serving_backpressure_trims_total)")
        self.uri = uri
        self.timeout = timeout


def encode_ndarray(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_ndarray(s: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(s)), allow_pickle=False)


class API:
    """Shared connection state (reference client.py:25-56).

    ``model`` routes to a per-model stream (serving/router.py);
    ``stream`` overrides the stream name directly.  Default: the
    single-tenant ``image_stream``."""

    def __init__(self, broker=None, host: str = "localhost",
                 port: int = 6379, model: str | None = None,
                 stream: str | None = None):
        if broker is None:
            broker = f"{host}:{port}"
        self.db = connect_broker(broker)
        self.stream = stream if stream is not None else (
            model_stream(model) if model else INPUT_STREAM)


class InputQueue(API):
    def enqueue_image(self, uri: str, data) -> None:
        """Push one record.  ``data``: ndarray, or a path to ``.npy`` /
        an image file (decoded via PIL when available).

        When an admission controller guards this stream
        (serving/admission.py publishes its verdict under
        ``admission:<stream>``), a shedding verdict raises
        :class:`ServingRejected` BEFORE the record is added — the one
        extra broker read per enqueue is the price of never trimming
        accepted work."""
        if isinstance(data, str):
            if data.endswith(".npy"):
                data = np.load(data)
            else:
                try:
                    from PIL import Image
                except ImportError as e:
                    raise ImportError(
                        "decoding image files needs PIL; pass an ndarray "
                        "or .npy path instead") from e
                data = np.asarray(Image.open(data))
        arr = np.asarray(data)
        verdict = self.db.hgetall(ADMISSION_KEY_PREFIX + self.stream)
        if verdict and verdict.get("state") == "shed":
            raise ServingRejected(
                uri,
                retry_after_s=float(verdict.get("retry_after_ms", 1000.0))
                / 1e3,
                reason=verdict.get("reason", ""))
        self.db.xadd(self.stream, {"uri": uri,
                                   "image": encode_ndarray(arr)})

    enqueue = enqueue_image

    def backlog(self) -> int:
        return self.db.xlen(self.stream)


class OutputQueue(API):
    def query(self, uri: str):
        """Result for one uri, or None if not ready (client.py:142)."""
        h = self.db.hgetall(RESULT_PREFIX + uri)
        if not h:
            return None
        return _decode_result(h)

    def poll(self, uri: str, timeout: float = 30.0,
             initial_delay: float = 0.005, max_delay: float = 0.25):
        """Block until the result for ``uri`` arrives; raise
        :class:`ServingTimeout` after ``timeout`` seconds.

        Polling backs off exponentially from ``initial_delay`` up to
        ``max_delay`` — a just-served result returns in milliseconds,
        while a slow batch costs at most ``max_delay`` staleness and a
        LOST record (trimmed under backpressure, undecodable) costs a
        bounded number of broker round-trips instead of a spin loop that
        hammers the broker forever."""
        deadline = time.monotonic() + timeout
        delay = max(initial_delay, 1e-4)
        while True:
            res = self.query(uri)
            if res is not None:
                return res
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ServingTimeout(uri, timeout)
            time.sleep(min(delay, remaining))
            delay = min(delay * 2, max_delay)

    def dequeue(self) -> dict:
        """All finished results keyed by uri, removing them from the
        broker (reference client.py:131 ``dequeue``)."""
        out = {}
        for key in self.db.keys(RESULT_PREFIX):
            h = self.db.hgetall(key)
            if not h:
                continue
            # key on the uri stored IN the hash: broker key names may be
            # transport-mangled (FileBroker replaces "/")
            uri = h.get("uri", key[len(RESULT_PREFIX):])
            out[uri] = _decode_result(h)
            self.db.delete(key)
        return out


def _decode_result(h: dict):
    if "value" in h:
        import json
        return json.loads(h["value"])
    if "tensor" in h:
        return decode_ndarray(h["tensor"])
    return h
