"""Runtime lockdep + guarded-by sanitizer ("zoosan" dynamic half).

The static tier (:mod:`rules_interproc`) proves properties of the code
it can see; this module proves the *annotations* against what actually
happens: every ``threading.Lock``/``RLock`` the package creates is
wrapped (when ``ZOO_SAN=1``) and three checkers run on the live
process:

- **lockdep** — a per-process lock-acquisition-order graph keyed by
  lock *class* (the ``file:line`` allocation site, the kernel-lockdep
  trick: every ``Broker._cv`` instance is one node).  Acquiring B
  while holding A adds the edge A->B; the first edge that closes a
  cycle produces one structured :class:`Finding` carrying BOTH stacks
  — the one that took A-then-B and the one now taking B-then-A — so
  the deadlock is debuggable from a single run that never actually
  deadlocked.
- **guarded-by validation** — classes whose source declares
  ``# guarded-by: <lock>`` (the Tier-1 annotation) get their
  ``__setattr__`` instrumented: an attribute assignment without the
  declared lock held by the current thread is a finding.  This is the
  cross-check that the annotations the static tier trusts are the
  locking discipline the program actually follows.  (Item writes and
  mutating calls stay static-tier-only — ``__setattr__`` cannot see
  them.)
- **blocking-under-lock** — ``queue.Queue.put/get`` with
  ``timeout=None``, ``time.sleep`` and ``socket.recv`` while holding
  any sanitized lock: the shapes that turn one slow peer into a
  stalled lock convoy.

Cost model: with ``ZOO_SAN`` unset nothing is touched —
``maybe_install()`` returns before any patch, ``threading.Lock``
stays ``_thread.allocate_lock`` (identity-checked by the test suite).
Enabled, only locks ALLOCATED from watched paths (the package tree
plus :func:`watch_path` additions) are wrapped; foreign locks
(logging, queue internals, jax) stay raw.

Findings are passive: they land in :func:`findings`, the
``zoo_san_findings_total{rule=}`` counter and one ``san_finding``
flight-recorder event each — the quick tier runs under ``ZOO_SAN=1``
and a finding fails the run only where a test asserts on it (or via
the conftest strict gate, ``ZOO_SAN_STRICT=1``).
"""

from __future__ import annotations

import importlib.abc
import importlib.machinery
import os
import sys
import threading
import traceback
from dataclasses import dataclass, field

from analytics_zoo_tpu.analysis.findings import Finding, Severity

__all__ = ["enabled", "installed", "maybe_install", "install",
           "uninstall", "watch_path", "findings", "drain",
           "instrument_module", "SanLock", "SanRLock"]

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# -- real primitives, captured before any patching --------------------------
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = None  # captured at install (time may be patched by tests)

_STACK_LIMIT = 16

#: frames never charged as a lock's allocation site (stdlib plumbing
#: between the package line and the actual allocation)
_SKIP_FILES = frozenset({threading.__file__, __file__})


@dataclass
class _State:
    """All sanitizer state; a fresh one per install keeps tests clean."""

    watched: list = field(default_factory=list)
    #: (outer_class, inner_class) -> formatted stack of the acquisition
    edges: dict = field(default_factory=dict)
    #: cycle pairs already reported (frozenset of lock classes)
    reported: set = field(default_factory=set)
    #: (rule, file, line) sites already reported (one finding per site)
    reported_sites: set = field(default_factory=set)
    findings: list = field(default_factory=list)
    #: path -> LintModule (or None), for static-suppression lookups
    parsed: dict = field(default_factory=dict)
    #: instrumented classes -> original __setattr__
    instrumented: dict = field(default_factory=dict)
    lock: object = field(default_factory=_REAL_LOCK)


_state: _State | None = None
_tls = threading.local()


def enabled() -> bool:
    """True when the env opts in (``ZOO_SAN=1``)."""
    return os.environ.get("ZOO_SAN", "") == "1"


def installed() -> bool:
    return _state is not None


def _held() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    elif stack:
        # a Lock may legally be released by ANOTHER thread (handoff
        # pattern); that release cannot reach this thread's list, so
        # prune entries we no longer own lazily — else the phantom
        # hold feeds false lockdep edges and blocking findings forever
        me = threading.get_ident()
        if any(e._owner != me for e in stack):
            stack[:] = [e for e in stack if e._owner == me]
    return stack


def _in_san() -> bool:
    return getattr(_tls, "in_san", False)


class _san_section:
    """Reentrancy guard: finding/metric recording acquires package
    locks (the registry's own children), which must not re-enter the
    bookkeeping."""

    def __enter__(self):
        self.prev = getattr(_tls, "in_san", False)
        _tls.in_san = True

    def __exit__(self, *exc):
        _tls.in_san = self.prev


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack(
        sys._getframe(skip), limit=_STACK_LIMIT))


def _caller_site(skip: int = 2) -> tuple:
    f = sys._getframe(skip)
    return f.f_code.co_filename, f.f_lineno


#: a runtime rule also honors its static sibling's suppressions — the
#: two halves check ONE contract, so one reviewed justification covers
#: both (`# zoolint: disable=guarded-by -- why` silences the runtime
#: validator at that write site too)
_STATIC_SIBLINGS = {
    "san-guarded-by": ("guarded-by",),
    "san-lock-order": ("lock-order", "lock-order-global"),
    "san-blocking-under-lock": (),
}


def _suppressed_at(st: _State, rule: str, path: str, line: int) -> bool:
    mod = st.parsed.get(path, _MISSING)
    if mod is _MISSING:
        mod = None
        if os.path.exists(path):
            try:
                from analytics_zoo_tpu.analysis.astlint import parse_module

                with open(path, encoding="utf-8") as f:
                    mod = parse_module(f.read(), path)
            except (OSError, SyntaxError):
                mod = None
        with st.lock:
            st.parsed[path] = mod
    if mod is None:
        return False
    rules = mod.suppressed_rules_at(line)
    return bool(rules & ({rule, "all"}
                         | set(_STATIC_SIBLINGS.get(rule, ()))))


_MISSING = object()


def _record(rule: str, message: str, path: str, line: int,
            **data) -> Finding:
    finding = Finding(rule=rule, severity=Severity.ERROR, path=path,
                      line=line, message=message, data=data)
    st = _state
    if st is None:
        return finding
    with _san_section():
        if _suppressed_at(st, rule, path, line):
            return finding
        with st.lock:
            site = (rule, path, line)
            if site in st.reported_sites:
                return finding
            st.reported_sites.add(site)
            st.findings.append(finding)
        try:
            from analytics_zoo_tpu.metrics import (
                get_flight_recorder,
                get_registry,
            )
            get_registry().counter(
                "zoo_san_findings_total",
                "runtime sanitizer findings by rule",
                ("rule",)).labels(rule=rule).inc()
            get_flight_recorder().record(
                "san_finding", rule=rule, message=message,
                path=path, line=line)
        except Exception:
            pass  # telemetry is best-effort; the finding itself is kept
    return finding


# ---------------------------------------------------------------------------
# Lock wrappers + lockdep.
# ---------------------------------------------------------------------------

class _SanBase:
    """Shared acquire/release bookkeeping over a real primitive."""

    def __init__(self, real, lock_class: str):
        self._real = real
        self._lock_class = lock_class
        self._owner = None  #: thread id of the current holder
        self._count = 0

    # -- bookkeeping ------------------------------------------------
    def _note_acquired(self):
        if _in_san():
            return
        me = threading.get_ident()
        reentrant = self._owner == me and self._count > 0
        self._owner, self._count = me, self._count + 1
        held = _held()
        if not reentrant and _state is not None:
            for other in held:
                if other is self \
                        or other._lock_class == self._lock_class:
                    continue
                self._lockdep_edge(other)
        held.append(self)

    def _note_released(self):
        if _in_san():
            return
        self._count = max(0, self._count - 1)
        if self._count == 0:
            self._owner = None
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def _lockdep_edge(self, outer: "_SanBase"):
        st = _state
        if st is None:
            return
        edge = (outer._lock_class, self._lock_class)
        with _san_section():
            with st.lock:
                known = edge in st.edges
                if not known:
                    st.edges[edge] = _stack(skip=4)
                cycle = None if known else _path(
                    st.edges, self._lock_class, outer._lock_class)
                if cycle is None:
                    return
                key = frozenset(cycle)
                if key in st.reported:
                    return
                st.reported.add(key)
                reverse_stack = st.edges.get(
                    (cycle[0], cycle[1]), "<unavailable>")
                this_stack = st.edges[edge]
        path, line = _caller_site(skip=4)
        order = " -> ".join((outer._lock_class, self._lock_class)
                            + tuple(cycle[1:]))
        _record(
            "san-lock-order",
            f"lock cycle closed at runtime: took `{self._lock_class}` "
            f"while holding `{outer._lock_class}`, but the reverse "
            f"order was observed earlier ({order}) — ABBA deadlock "
            "shape; both stacks in data",
            path, line,
            cycle=[outer._lock_class, self._lock_class],
            this_stack=this_stack, reverse_stack=reverse_stack)

    def _held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident() and self._count > 0

    # -- delegated lock protocol ------------------------------------
    def acquire(self, *args, **kwargs):
        got = self._real.acquire(*args, **kwargs)
        if got:
            self._note_acquired()
        return got

    def release(self):
        self._note_released()
        self._real.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._real.locked()

    def _at_fork_reinit(self):
        # threading._after_fork reinitializes the locks inside Events/
        # Conditions of surviving threads — wrapped locks must speak it
        # or a fork-start child dies in the reinit walk
        self._real._at_fork_reinit()
        self._owner, self._count = None, 0

    def __repr__(self):
        return f"<{type(self).__name__} {self._lock_class} " \
               f"wrapping {self._real!r}>"


class SanLock(_SanBase):
    """``threading.Lock`` wrapper tracked by the sanitizer."""


class SanRLock(_SanBase):
    """``threading.RLock`` wrapper; also speaks the private Condition
    protocol (``_is_owned`` / ``_release_save`` / ``_acquire_restore``)
    so ``threading.Condition`` composes transparently."""

    def _is_owned(self):
        return self._real._is_owned()

    def _release_save(self):
        # Condition.wait(): the lock is fully released however deep the
        # recursion — mirror that in the held stack
        count = self._count
        while self._count > 0:
            self._note_released()
        state = self._real._release_save()
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        self._real._acquire_restore(state)
        for _ in range(count):
            self._note_acquired()


def _path(edges, start: str, target: str, limit: int = 8):
    """A path start -> ... -> target in the edge dict, or None."""
    adjacency: dict = {}
    for (a, b) in edges:
        adjacency.setdefault(a, []).append(b)
    stack = [(start, (start,))]
    visited = {start}
    while stack:
        node, trail = stack.pop()
        if len(trail) > limit:
            continue
        for nxt in adjacency.get(node, ()):
            if nxt == target:
                return trail + (nxt,)
            if nxt not in visited:
                visited.add(nxt)
                stack.append((nxt, trail + (nxt,)))
    return None


def _watched_site() -> str | None:
    """Allocation site ``file:line`` when the (nearest non-stdlib-
    threading) caller is in a watched tree, else None (foreign locks
    stay raw).  Skipping ``threading.py`` frames attributes the RLock
    a ``threading.Condition()`` creates internally to the package line
    that built the Condition."""
    st = _state
    if st is None:
        return None
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename in _SKIP_FILES:
        f = f.f_back
    if f is None:
        return None
    filename = f.f_code.co_filename
    for prefix in st.watched:
        if filename.startswith(prefix):
            rel = os.path.relpath(filename, prefix)
            return f"{rel}:{f.f_lineno}"
    return None


def _lock_factory():
    site = _watched_site()
    real = _REAL_LOCK()
    return real if site is None else SanLock(real, site)


def _rlock_factory():
    site = _watched_site()
    real = _REAL_RLOCK()
    return real if site is None else SanRLock(real, site)


# ---------------------------------------------------------------------------
# Blocking-call detection.
# ---------------------------------------------------------------------------

def _flag_blocking(what: str, skip: int = 2):
    held = _held()
    st = _state
    if not held or _in_san() or st is None:
        return
    path, line = _caller_site(skip)
    locks = ", ".join(h._lock_class for h in held)
    _record(
        "san-blocking-under-lock",
        f"{what} while holding lock(s) [{locks}] — an unbounded wait "
        "under a lock turns one slow peer into a convoy; release the "
        "lock first or use a timeout",
        path, line, call=what, locks=[h._lock_class for h in held])


def _make_sleep(real_sleep):
    def sleep(seconds):
        _flag_blocking(f"time.sleep({seconds!r})", skip=3)
        return real_sleep(seconds)
    sleep._zoo_san = True
    return sleep


def _make_queue_method(real, name):
    # put(self, item, block=True, timeout=None) / get(self, block=True,
    # timeout=None): positional offsets differ by the item argument
    first = 1 if name == "put" else 0

    def method(self, *args, **kwargs):
        block = args[first] if len(args) > first \
            else kwargs.get("block", True)
        timeout = args[first + 1] if len(args) > first + 1 \
            else kwargs.get("timeout", None)
        if block and timeout is None:
            _flag_blocking(f"queue.Queue.{name}(timeout=None)", skip=3)
        return real(self, *args, **kwargs)
    method._zoo_san = True
    return method


def _make_recv(real_recv):
    def recv(self, *args, **kwargs):
        if self.gettimeout() is None:
            _flag_blocking("socket.recv() with no socket timeout",
                           skip=3)
        return real_recv(self, *args, **kwargs)
    recv._zoo_san = True
    return recv


# ---------------------------------------------------------------------------
# Guarded-by runtime validation.
# ---------------------------------------------------------------------------

_EXEMPT_FRAMES = {"__init__", "__post_init__", "__new__", "__del__",
                  "__setstate__"}


def _class_guards(module) -> dict:
    """{class name: {attr: lock attr}} parsed from the module's source
    — the SAME annotations Tier 1 reads, so the two halves check one
    contract."""
    from analytics_zoo_tpu.analysis.astlint import parse_module
    from analytics_zoo_tpu.analysis.rules_concurrency import GuardedByRule

    import ast

    path = getattr(module, "__file__", None)
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            mod = parse_module(f.read(), path)
    except (OSError, SyntaxError):
        return {}
    rule = GuardedByRule()
    out = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            guards = rule._declared_guards(mod, node)
            if guards:
                out[node.name] = guards
    return out


def _unwrap_lock(obj):
    """The _SanBase behind a lock attribute (Conditions hold theirs at
    ``_lock``); None when the attribute is not a sanitized lock."""
    if isinstance(obj, _SanBase):
        return obj
    inner = getattr(obj, "_lock", None)  # threading.Condition
    if isinstance(inner, _SanBase):
        return inner
    return None


def _make_guarded_setattr(cls, guards: dict, orig):
    def __setattr__(self, name, value):
        if name in guards and _state is not None and not _in_san():
            lock = _unwrap_lock(getattr(self, guards[name], None))
            if lock is not None and not lock._held_by_current_thread():
                caller = sys._getframe(1)
                if caller.f_code.co_name not in _EXEMPT_FRAMES:
                    _record(
                        "san-guarded-by",
                        f"write to `{cls.__name__}.{name}` (declared "
                        f"guarded-by `{guards[name]}`) without the "
                        f"lock held by this thread — the annotation "
                        "the static tier trusts does not hold at "
                        "runtime",
                        caller.f_code.co_filename, caller.f_lineno,
                        cls=cls.__name__, attribute=name,
                        lock=guards[name], stack=_stack(skip=2))
        orig(self, name, value)
    __setattr__._zoo_san = True
    return __setattr__


def instrument_module(module) -> int:
    """Instrument every ``# guarded-by``-annotated class defined in
    ``module``; returns the number of classes wrapped.  Idempotent."""
    st = _state
    if st is None:
        return 0
    guards_by_class = _class_guards(module)
    n = 0
    for name, cls in list(vars(module).items()):
        if not isinstance(cls, type) \
                or cls.__module__ != module.__name__ \
                or cls.__name__ not in guards_by_class \
                or cls in st.instrumented:
            continue
        orig = cls.__setattr__
        if getattr(orig, "_zoo_san", False):
            continue
        cls.__setattr__ = _make_guarded_setattr(
            cls, guards_by_class[cls.__name__], orig)
        st.instrumented[cls] = orig
        n += 1
    return n


class _SanImportHook(importlib.abc.MetaPathFinder,
                     importlib.abc.Loader):
    """Instruments watched modules' guarded classes as they import."""

    def __init__(self, prefixes):
        self.prefixes = tuple(prefixes)

    def find_spec(self, fullname, path=None, target=None):
        if not any(fullname == p or fullname.startswith(p + ".")
                   for p in self.prefixes):
            return None
        spec = importlib.machinery.PathFinder.find_spec(fullname, path)
        if spec is None or spec.loader is None:
            return None
        spec.loader = _WrapLoader(spec.loader)
        return spec


class _WrapLoader(importlib.abc.Loader):
    def __init__(self, inner):
        self.inner = inner

    def create_module(self, spec):
        return self.inner.create_module(spec)

    def exec_module(self, module):
        self.inner.exec_module(module)
        if installed():
            instrument_module(module)

    def __getattr__(self, name):  # is_package etc. for importlib
        return getattr(self.inner, name)


# ---------------------------------------------------------------------------
# Install / uninstall.
# ---------------------------------------------------------------------------

_patches: list = []  # (obj, attr, original) for uninstall
_import_hook: _SanImportHook | None = None


def watch_path(prefix: str) -> None:
    """Also wrap locks allocated under ``prefix`` (tests use this for
    planted fixture modules)."""
    if _state is not None:
        p = os.path.abspath(prefix)
        if p not in _state.watched:
            _state.watched.append(p)


def findings() -> list:
    """Snapshot of the findings recorded so far."""
    if _state is None:
        return []
    with _state.lock:
        return list(_state.findings)


def drain() -> list:
    """Return AND clear the recorded findings, re-arming the per-site
    dedup (test isolation)."""
    if _state is None:
        return []
    with _state.lock:
        out = list(_state.findings)
        _state.findings.clear()
        _state.reported_sites.clear()
        _state.reported.clear()
    return out


def _patch(obj, attr, replacement):
    _patches.append((obj, attr, getattr(obj, attr)))
    setattr(obj, attr, replacement)


def install(extra_paths=()) -> None:
    """Activate the sanitizer (idempotent).  Wraps lock creation for
    watched paths, hooks the blocking calls, and starts instrumenting
    guarded classes (already-imported watched modules immediately,
    later imports via a meta-path hook)."""
    global _state, _import_hook, _REAL_SLEEP
    if _state is not None:
        return
    import queue
    import socket
    import time

    _state = _State(watched=[_PACKAGE_ROOT]
                    + [os.path.abspath(p) for p in extra_paths])
    _REAL_SLEEP = time.sleep

    _patch(threading, "Lock", _lock_factory)
    _patch(threading, "RLock", _rlock_factory)
    _patch(time, "sleep", _make_sleep(time.sleep))
    _patch(queue.Queue, "put", _make_queue_method(queue.Queue.put, "put"))
    _patch(queue.Queue, "get", _make_queue_method(queue.Queue.get, "get"))
    try:
        _patch(socket.socket, "recv", _make_recv(socket.socket.recv))
    except (AttributeError, TypeError):
        pass  # immutable socket type on this platform: skip the probe

    _import_hook = _SanImportHook(["analytics_zoo_tpu"])
    sys.meta_path.insert(0, _import_hook)
    for name, module in list(sys.modules.items()):
        if name == "analytics_zoo_tpu" \
                or name.startswith("analytics_zoo_tpu."):
            instrument_module(module)


def uninstall() -> None:
    """Remove every patch and drop the state (test isolation; NOT run
    in production — the wrappers are harmless for a process lifetime)."""
    global _state, _import_hook
    if _state is None:
        return
    for cls, orig in _state.instrumented.items():
        cls.__setattr__ = orig
    while _patches:
        obj, attr, original = _patches.pop()
        setattr(obj, attr, original)
    if _import_hook is not None:
        try:
            sys.meta_path.remove(_import_hook)
        except ValueError:
            pass
        _import_hook = None
    _state = None


def maybe_install() -> bool:
    """The zero-cost gate the package ``__init__`` calls: installs iff
    ``ZOO_SAN=1``; with the env unset NOTHING is touched
    (``threading.Lock`` keeps its builtin identity)."""
    if not enabled():
        return False
    install()
    return True
