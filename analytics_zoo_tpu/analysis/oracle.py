"""ConfigOracle — the predictive compile plane's decision surface.

:mod:`analytics_zoo_tpu.analysis.costmodel` predicts; this module
DECIDES and is wired in as the prior for the two consumers that used
to search blind:

- the autotuner's K hill-climb (feature/autotune.py) calls
  :meth:`ConfigOracle.predict_k` after the first compiled dispatch and
  jumps straight to the predicted ``steps_per_dispatch``, demoting the
  ladder sweep to a ±1-neighbor validation pass — ≤8 dispatches to
  settle instead of ~53 (BENCH_AUTOTUNE_r08), trajectory still
  bitwise-equal because per-inner-step RNG folds on the global step
  index regardless of the K schedule;
- ``estimator.fit(plan="auto")`` calls :meth:`ConfigOracle.choose_plan`
  to pick among dp/zero1/fsdp/tp from predicted per-chip bytes vs the
  HBM budget, preferring the least-collective-traffic plan that fits.

Every prediction→outcome pair is logged three ways (the autotune
convention): the ``zoo_oracle_*`` metric family, an ``oracle`` flight
event, and a bounded predicted-vs-measured table served at ``/varz``
(rendered by ``tools/metrics_dump.py``) — closing the data loop the
residual model trains on.  Opt-out: ``ZOO_ORACLE=0`` restores the
blind sweep everywhere.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
import weakref
from typing import Iterable, Mapping, Sequence

from analytics_zoo_tpu.analysis.costmodel import (
    DTYPE_PEAK_FACTORS,
    REMAT_FLOPS_FACTORS,
    PeakTable,
    ResidualModel,
    choose_kernel,
    normalize_features,
    plan_collective_bytes,
    plan_exposed_fraction,
    predict_chip_bytes,
    predict_serving_seconds,
    predict_step_seconds,
    predict_steps_per_sec,
    resolve_peaks,
    training_rows,
)
from analytics_zoo_tpu.metrics import (
    OracleMetrics,
    get_flight_recorder,
)

__all__ = ["ConfigOracle", "oracle_enabled", "varz_doc",
           "KERNEL_STEP_FACTORS", "SERVING_SLO_FRACTION",
           "SERVING_UTILIZATION"]

#: plans the oracle can choose among for ``plan="auto"``, ordered from
#: least to most sharded so infeasible-everywhere ties break toward the
#: established layout (fsdp before the equivalent-memory zero3) —
#: tensor parallelism needs a model-specific rule table and pipeline a
#: staged model, so they participate in ranking only when the caller
#: passes them explicitly
DEFAULT_PLAN_CANDIDATES = ("dp", "zero1", "zero2", "fsdp", "zero3")

#: a prediction within this margin of the best is "as good" — ties go
#: to the smaller K (finer checkpoint cadence), mirroring the
#: autotuner's own k_margin settle rule
PREDICT_MARGIN = 0.05

#: Step-time factor the KERNEL dimension applies to a candidate's
#: compute term in :meth:`ConfigOracle.choose_plan`.  On TPU the fused
#: Pallas kernels cut the optimizer/loss HBM round trips ~2.5-3x
#: (costmodel.kernel_bytes: fused_adam 24n vs 60n, fused_softmax_xent
#: 4BV vs 12BV) but those scopes are a slice of the whole step, so the
#: ranking coefficient is a modest 0.9 — it exists to ORDER "+kernels"
#: above its plain twin on TPU, like the plan_collective_bytes
#: coefficients, not to predict seconds.  On non-TPU peaks the factor
#: is exactly 1.0: the kernels fall back to the same XLA program, so
#: the tie breaks toward the plain candidate (candidate order) — the
#: oracle DECLINING pallas on the CPU tier.
KERNEL_STEP_FACTORS = {None: 1.0, "kernels": 0.9}

#: Share of the p99 SLO :meth:`ConfigOracle.choose_serving` budgets for
#: SERVICE time (the padded dispatch itself); the remainder is queueing
#: headroom — Little's-law delay under the target utilization plus the
#: batcher's fill wait.  A bucket whose predicted dispatch exceeds this
#: slice of the SLO cannot meet the tail even on an idle replica, so it
#: is excluded from the pad-bucket set.
SERVING_SLO_FRACTION = 0.5

#: Per-replica utilization the replica math plans to: predicted
#: capacity is derated by this factor so the fleet absorbs arrival
#: burstiness without the queue estimate blowing through the SLO
#: headroom (the classic M/M/1 knee — above ~0.7 the queue term
#: dominates).
SERVING_UTILIZATION = 0.6


def oracle_enabled() -> bool:
    """``ZOO_ORACLE`` gate (default ON — the oracle only reorders
    searches, it never changes results; ``0``/``false``/``off``
    restores the blind sweep)."""
    return os.environ.get("ZOO_ORACLE", "1").strip().lower() not in (
        "0", "false", "off")


# ---------------------------------------------------------------------------
# Live-oracle registry: /varz (metrics/http.py) includes the
# predicted-vs-measured tables of whatever oracles exist, via
# sys.modules only — metrics-only processes never import this module.
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[ConfigOracle]" = (  # guarded-by: _active_lock
    weakref.WeakSet())


def varz_doc() -> dict:
    """The ``oracle`` section of ``/varz``: every live oracle's peak
    table, residual-fit size, and merged time-ordered
    prediction→outcome log."""
    with _active_lock:
        oracles = list(_active)
    docs = [o.to_doc() for o in oracles]
    predictions = sorted(
        (p for doc in docs for p in doc["predictions"]),
        key=lambda p: p["ts"])
    return {"oracles": docs, "predictions": predictions}


class ConfigOracle:
    """Ranks candidate (K, sharding plan) configs from the analytic
    roofline, corrected by the fitted residual once enough outcome
    history exists.

    One oracle serves one process; build with :meth:`from_env` to get
    platform-resolved peaks and a residual fitted from whatever
    ``ZOO_HLO_REPORT_DIR`` / ``ZOO_TUNE_LOG_DIR`` history has
    accumulated.  All prediction state is lock-guarded — the autotuner
    consults it from the estimator loop while /varz snapshots it from
    the HTTP thread."""

    def __init__(self, peaks: PeakTable | None = None,
                 residual: ResidualModel | None = None,
                 registry=None, log_capacity: int = 256):
        self.peaks = peaks if peaks is not None else resolve_peaks()
        self.residual = residual if residual is not None else \
            ResidualModel(peaks=self.peaks)
        self.metrics = OracleMetrics(registry=registry)
        self._lock = threading.Lock()
        # config key -> the latest prediction record for it (outcome
        # fields filled in when record_outcome closes the pair)
        self._pairs: "collections.OrderedDict[str, dict]" = (  # guarded-by: _lock
            collections.OrderedDict())
        self._log_capacity = int(log_capacity)
        self.metrics.fit_samples.set(self.residual.n_samples)
        with _active_lock:
            _active.add(self)

    # ------------------------------------------------------------------
    # construction from the env tier
    # ------------------------------------------------------------------
    @classmethod
    def from_env(cls, registry=None) -> "ConfigOracle":
        """Platform-resolved peaks (device kind when jax is up,
        ``ZOO_ORACLE_PEAKS`` override last) + a residual model fitted
        from the accumulated report/tune-log history — analytic-only
        when nothing has accumulated yet."""
        platform = kind = None
        try:
            import jax

            devices = jax.devices()
            if devices:
                platform = devices[0].platform
                kind = devices[0].device_kind
        except Exception:
            pass
        oracle = cls(peaks=resolve_peaks(platform, kind),
                     registry=registry)
        oracle.refit()
        return oracle

    def refit(self, rows: Iterable[Mapping] | None = None) -> int:
        """(Re)fit the residual from ``rows``, or from the env-dir
        history (``ZOO_HLO_REPORT_DIR`` joined with ``ZOO_TUNE_LOG_DIR``)
        when not given.  Returns the fitted sample count — 0 means the
        oracle stays analytic."""
        rows = list(rows) if rows is not None else training_rows()
        self.residual.fit(rows)
        self.metrics.fit_samples.set(self.residual.n_samples)
        return self.residual.n_samples

    # ------------------------------------------------------------------
    # prediction surface
    # ------------------------------------------------------------------
    def predict_steps_per_sec(self, features: Mapping, k: int = 1) -> float:
        """Fitted prediction when the residual is ready, pure analytic
        roofline otherwise — callers never branch on readiness."""
        return self.residual.predict_steps_per_sec(features, k=k)

    def predict_k(self, features: Mapping,
                  k_candidates: Sequence[int]) -> int:
        """The ``steps_per_dispatch`` the autotuner should START at:
        smallest candidate whose predicted steps/sec is within
        :data:`PREDICT_MARGIN` of the best (the autotuner's own settle
        tie-break).  Predictions for EVERY candidate are logged, so
        whatever K the ±1 validation pass settles on has a recorded
        prediction to score against."""
        preds = {int(k): self.predict_steps_per_sec(features, k=k)
                 for k in k_candidates}
        best = max(preds.values())
        k_hat = min(k for k, sps in preds.items()
                    if sps >= best * (1.0 - PREDICT_MARGIN))
        now = time.time()
        with self._lock:
            for k, sps in sorted(preds.items()):
                self._remember_locked({
                    "ts": now, "consumer": "autotune_k",
                    "config": f"k={k}", "predicted_steps_per_sec": sps,
                    "chosen": k == k_hat,
                    "measured_steps_per_sec": None, "rel_error": None})
        self.metrics.predictions.labels(consumer="autotune_k").inc()
        self.metrics.predicted_sps.labels(
            config=f"k={k_hat}").set(preds[k_hat])
        get_flight_recorder().record(
            "oracle", consumer="autotune_k", config=f"k={k_hat}",
            predicted_steps_per_sec=round(preds[k_hat], 3),
            fit_samples=self.residual.n_samples)
        return k_hat

    def choose_plan(self, param_bytes: int, opt_bytes: int,
                    n_shards: int, hbm_budget: int | None = None,
                    features: Mapping | None = None,
                    plans: Sequence[str] = DEFAULT_PLAN_CANDIDATES,
                    batch_bytes: int = 0,
                    activation_bytes: int = 0,
                    remat_options: Sequence[str | None] = (None,),
                    dtype_options: Sequence[str | None] = (None,),
                    kernel_options: Sequence[str | None] = (None,),
                    ) -> tuple[str, dict]:
        """The sharding plan ``plan="auto"`` resolves to: among the
        (plan × remat) candidates whose predicted per-chip bytes fit
        the HBM budget, the one whose predicted step time (roofline ×
        the remat recompute factor, plus the *exposed* slice of the
        plan's per-step collective traffic over the link ceiling —
        ``+overlap`` candidates hide the rest behind compute, serial
        plans expose all of it) is lowest — i.e. the
        least-sharded, least-rematted feasible config, since sharding
        only adds collectives and remat only adds FLOPs.  Ties keep
        candidate order.  Returns ``(plan_name, doc)``; the doc records
        every candidate's predicted bytes/traffic/feasibility plus
        ``chosen_remat`` (``None`` unless a remat policy was needed to
        fit).  ``remat_options`` defaults to no-remat-only, so existing
        callers sweep exactly the old space; ``fit(plan="auto")``
        passes ``(None, "full")`` and an activation estimate to sweep
        the full memory plan.  Infeasible-everywhere falls back to the
        most memory-frugal candidate (training may still OOM, but that
        config is the only one with a chance).

        ``dtype_options`` adds the PRECISION dimension (dtype-dependent
        ceilings, DTYPE_PEAK_FACTORS): a ``"bf16"`` candidate's compute
        term shrinks by the dtype's matmul-rate factor and its
        fsdp/zero3 gather traffic by the element-size ratio (the
        f32-accumulation contract keeps gradient collectives f32), so
        the oracle can trade precision for speed under an SLO or HBM
        budget.  Defaults to f32-only — existing callers sweep exactly
        the old space; the estimator passes ``(None, "bf16")`` when
        ``ZOO_DTYPE_POLICY=auto``.

        ``kernel_options`` adds the KERNEL dimension
        (:data:`KERNEL_STEP_FACTORS`): a ``"kernels"`` candidate's
        compute term scales by the fused-kernel factor ON TPU PEAKS
        ONLY — on any other platform the factor is 1.0 and the tie
        breaks toward the plain candidate (candidate order), so the
        CPU tier declines pallas by construction.  Defaults to
        no-kernels-only; the estimator passes ``(None, "kernels")``
        under ``ZOO_USE_PALLAS=1``."""
        budget = int(hbm_budget) if hbm_budget else int(self.peaks.hbm_bytes)
        feats = features or {}
        base_s = 1.0 / self.predict_steps_per_sec(feats, k=1)
        on_tpu = self.peaks.source.lower().startswith("tpu")
        candidates = []
        for dtype in dtype_options:
            dfact = DTYPE_PEAK_FACTORS[dtype if dtype else "f32"]
            for remat in remat_options:
                for plan in plans:
                    chip = predict_chip_bytes(
                        param_bytes, opt_bytes, plan, n_shards,
                        batch_bytes=batch_bytes,
                        activation_bytes=activation_bytes, remat=remat,
                        dtype=dtype)
                    coll = plan_collective_bytes(
                        param_bytes, plan, n_shards, dtype=dtype)
                    coll_s = coll / max(self.peaks.link_bytes_per_s, 1.0)
                    # Overlap-aware roofline: a "+overlap" candidate
                    # hides (1 - exposed) of its collective time behind
                    # compute, so only the exposed slice is additive.
                    # Serial plans have exposed == 1.0, which reduces to
                    # the old purely additive formula bit-for-bit — the
                    # default candidate sweep (and fit(plan="auto")
                    # agreement with it) is unchanged.
                    exposed = plan_exposed_fraction(plan)
                    for kern in kernel_options:
                        kfact = (KERNEL_STEP_FACTORS[kern]
                                 if on_tpu else 1.0)
                        compute_s = (base_s * REMAT_FLOPS_FACTORS[remat]
                                     / dfact["flops"] * kfact)
                        step_s = (max(compute_s,
                                      coll_s * (1.0 - exposed))
                                  + coll_s * exposed)
                        config = f"plan={plan}" if remat is None \
                            else f"plan={plan}+remat_{remat}"
                        if dtype:
                            config += f"+{dtype}"
                        if kern:
                            config += "+kernels"
                        candidates.append({
                            "plan": plan, "remat": remat,
                            "dtype": dtype, "kernels": kern,
                            "config": config,
                            "predicted_chip_bytes": chip,
                            "predicted_collective_bytes_per_step": coll,
                            "predicted_steps_per_sec":
                                round(1.0 / step_s, 3),
                            "fits_budget": chip <= budget})
        feasible = [c for c in candidates if c["fits_budget"]]
        pool = feasible or sorted(
            candidates, key=lambda c: c["predicted_chip_bytes"])[:1]
        chosen = max(pool, key=lambda c: c["predicted_steps_per_sec"])
        doc = {"chosen": chosen["plan"], "chosen_remat": chosen["remat"],
               "chosen_dtype": chosen["dtype"],
               "chosen_kernels": chosen["kernels"],
               "chosen_config": chosen["config"],
               "hbm_budget_bytes": budget,
               "n_shards": int(n_shards), "param_bytes": int(param_bytes),
               "opt_bytes": int(opt_bytes),
               "activation_bytes": int(activation_bytes),
               "candidates": candidates,
               "feasible": bool(feasible)}
        now = time.time()
        with self._lock:
            for c in candidates:
                self._remember_locked({
                    "ts": now, "consumer": "plan_auto",
                    "config": c["config"],
                    "predicted_steps_per_sec":
                        c["predicted_steps_per_sec"],
                    "chosen": c is chosen,
                    "measured_steps_per_sec": None, "rel_error": None})
        self.metrics.predictions.labels(consumer="plan_auto").inc()
        self.metrics.predicted_sps.labels(
            config=chosen["config"]).set(
                chosen["predicted_steps_per_sec"])
        get_flight_recorder().record(
            "oracle", consumer="plan_auto", config=chosen["config"],
            chip_bytes=chosen["predicted_chip_bytes"],
            hbm_budget=budget, feasible=bool(feasible))
        return chosen["plan"], doc

    def choose_kernels(self, kernel_sizes: Mapping[str, Mapping],
                       platform: str | None = None) -> dict:
        """Per-kernel kernel-vs-XLA verdicts for the kernel plane.

        ``kernel_sizes`` maps kernel name → the size kwargs its byte
        model needs (:func:`~analytics_zoo_tpu.analysis.costmodel
        .kernel_bytes`), e.g. ``{"fused_adam": {"n": 4096}}``.
        ``platform`` defaults to the peak table's source, so an oracle
        built from CPU peaks declines every kernel (Pallas lowers via
        Mosaic) and one built from TPU peaks picks by the analytic byte
        model.  Every verdict is a logged prediction under
        ``config="kernel=<name>"`` — the bench's measured per-variant
        steps/sec closes the pair via :meth:`record_outcome`."""
        platform = platform or self.peaks.source
        verdicts = {}
        now = time.time()
        for name, sizes in kernel_sizes.items():
            v = choose_kernel(name, platform=platform, peaks=self.peaks,
                              **sizes)
            verdicts[name] = v
            sps = 1.0 / max(v["predicted_s"][
                "kernel" if v["choice"] == name else "xla"], 1e-12)
            with self._lock:
                self._remember_locked({
                    "ts": now, "consumer": "kernel_plane",
                    "config": f"kernel={name}",
                    "predicted_steps_per_sec": round(sps, 3),
                    "chosen": v["choice"] == name,
                    "measured_steps_per_sec": None, "rel_error": None})
            self.metrics.predictions.labels(
                consumer="kernel_plane").inc()
            self.metrics.predicted_sps.labels(
                config=f"kernel={name}").set(round(sps, 3))
            get_flight_recorder().record(
                "oracle", consumer="kernel_plane",
                config=f"kernel={name}", choice=v["choice"],
                predicted_kernel_bytes=v["predicted_bytes"]["kernel"],
                predicted_xla_bytes=v["predicted_bytes"]["xla"])
        return verdicts

    def choose_serving(self, model_features, slo_p99_ms: float,
                       offered_rate: float, model: str = "default",
                       max_replicas: int = 8,
                       kernel_sizes: Mapping[str, Mapping] | None = None,
                       ) -> dict:
        """The serving config a model should be PRIMED with before its
        first request — the TpuGraphs cost-model plane applied to
        inference (ISSUE 20).

        ``model_features`` is the per-bucket feature source: either the
        row list :func:`~analytics_zoo_tpu.analysis.costmodel
        .load_serving_rows` returns (one ``inference_b<bucket>`` report
        row per pad bucket, produced by ``InferenceModel.warmup`` under
        ``ZOO_HLO_REPORT_DIR``) or a plain ``{bucket: features}``
        mapping.  Per bucket the serving roofline
        (:func:`predict_serving_seconds`, corrected by the fitted
        residual once it is ready) predicts one dispatch's wall
        seconds; from those predictions the oracle derives

        - **pad_buckets** — buckets whose predicted dispatch fits the
          service slice of the SLO (:data:`SERVING_SLO_FRACTION`); the
          smallest bucket always qualifies so the set is never empty;
        - **replicas** — ``ceil(offered_rate / capacity)`` where
          capacity is the best bucket's ``bucket/seconds`` derated by
          :data:`SERVING_UTILIZATION`, clamped to ``[1, max_replicas]``
          — the :class:`~analytics_zoo_tpu.serving.scaler.SloScaler`
          prior target, so the fleet starts AT the predicted size
          instead of discovering it through a violation;
        - **batch_budget_ms** — the ``ZOO_SERVING_BATCH_BUDGET_MS``
          slice left after the best bucket's service time, i.e. how
          long the batcher may wait filling a bucket without eating the
          tail headroom;
        - **quantize** — ``"int8"`` exactly when the predict program is
          memory-bound (weight-stationary int8 quarters HBM traffic —
          ``quantize_params_for_plan`` applies it plan-aware); a
          dispatch- or compute-bound program keeps f32;
        - **kernels** — per-kernel verdicts via :meth:`choose_kernels`
          when ``kernel_sizes`` is given (CPU peaks decline by
          construction).

        Every per-bucket prediction is a logged pair under
        ``config="serving:<model>:b<bucket>"`` (dispatches/sec); the
        bench's measured per-bucket latency closes them via
        :meth:`record_outcome`.  Returns the config doc the router
        primes a fleet from."""
        slo_s = float(slo_p99_ms) / 1e3
        if slo_s <= 0:
            raise ValueError(f"slo_p99_ms must be > 0, got {slo_p99_ms}")
        rows: dict[int, Mapping] = {}
        dtype_hists: dict[int, Mapping | None] = {}
        if isinstance(model_features, Mapping):
            for bucket, feats in model_features.items():
                rows[int(bucket)] = feats or {}
                dtype_hists[int(bucket)] = None
        else:
            for row in model_features or ():
                bucket = int(row.get("bucket") or 0)
                if bucket <= 0:
                    continue
                rows[bucket] = row.get("features") or {}
                dtype_hists[bucket] = row.get("dtype_histogram")
        predicted: dict[str, dict] = {}
        feasible: list[int] = []
        for bucket in sorted(rows):
            feats = rows[bucket]
            pred_s = predict_serving_seconds(
                feats, batch=bucket, peaks=self.peaks,
                dtype_histogram=dtype_hists.get(bucket))
            if self.residual.ready:
                # the residual is fitted on step seconds from the SAME
                # feature vector; apply its correction as a ratio so
                # the serving-specific terms (per-call overhead, batch
                # scaling) survive
                analytic_s = predict_step_seconds(
                    feats, k=1, peaks=self.peaks)
                fitted_s = 1.0 / max(
                    self.residual.predict_steps_per_sec(feats, k=1),
                    1e-12)
                pred_s *= fitted_s / max(analytic_s, 1e-12)
            fits = pred_s <= slo_s * SERVING_SLO_FRACTION
            if fits:
                feasible.append(bucket)
            predicted[str(bucket)] = {
                "bucket": bucket,
                "predict_seconds": pred_s,
                "capacity_rps":
                    bucket / max(pred_s, 1e-12) * SERVING_UTILIZATION,
                "feasible": fits,
            }
        if not feasible and rows:
            # nothing fits the service slice: serve at the smallest
            # bucket anyway (the only config with a chance), mirroring
            # choose_plan's infeasible-everywhere fallback
            feasible = [min(rows)]
        best = max(feasible) if feasible else 0
        if best:
            best_doc = predicted[str(best)]
            replicas = max(1, min(int(max_replicas), math.ceil(
                max(float(offered_rate), 0.0)
                / max(best_doc["capacity_rps"], 1e-12))))
            budget_ms = min(
                max((slo_s * SERVING_SLO_FRACTION
                     - best_doc["predict_seconds"]) * 1e3, 1.0),
                float(slo_p99_ms) * SERVING_SLO_FRACTION)
            f = normalize_features(rows[best])
            mem_s = f["bytes_accessed"] / max(
                self.peaks.hbm_bytes_per_s, 1.0)
            comp_s = f["matmul_flops"] / max(self.peaks.flops, 1.0)
            quantize = "int8" if mem_s > comp_s else None
        else:
            # zero feature rows (no warmup has run): conservative prior
            replicas, budget_ms, quantize = 1, slo_p99_ms / 4.0, None
        kernels = (self.choose_kernels(kernel_sizes)
                   if kernel_sizes else {})
        config = f"serving:{model}"
        doc = {
            "model": str(model), "config": config,
            "replicas": int(replicas),
            "pad_buckets": sorted(feasible),
            "batch_budget_ms": round(float(budget_ms), 3),
            "quantize": quantize, "kernels": kernels,
            "predicted": predicted,
            "slo_p99_ms": float(slo_p99_ms),
            "offered_rate": float(offered_rate),
            "fit_samples": self.residual.n_samples,
        }
        now = time.time()
        with self._lock:
            for key, p in sorted(predicted.items(),
                                 key=lambda kv: kv[1]["bucket"]):
                self._remember_locked({
                    "ts": now, "consumer": "serving",
                    "config": f"{config}:b{p['bucket']}",
                    "predicted_steps_per_sec":
                        round(1.0 / max(p["predict_seconds"], 1e-12), 3),
                    "chosen": p["bucket"] == best,
                    "measured_steps_per_sec": None, "rel_error": None})
        self.metrics.predictions.labels(consumer="serving").inc()
        for p in predicted.values():
            self.metrics.serving_predicted_seconds.labels(
                model=str(model), bucket=str(p["bucket"])).set(
                    p["predict_seconds"])
        self.metrics.serving_predicted_replicas.labels(
            model=str(model)).set(replicas)
        self.metrics.serving_predicted_budget_ms.labels(
            model=str(model)).set(doc["batch_budget_ms"])
        if best:
            self.metrics.predicted_sps.labels(config=config).set(
                round(1.0 / max(
                    predicted[str(best)]["predict_seconds"], 1e-12), 3))
        get_flight_recorder().record(
            "oracle", consumer="serving", config=config,
            replicas=int(replicas), pad_buckets=sorted(feasible),
            batch_budget_ms=doc["batch_budget_ms"],
            quantize=quantize,
            slo_p99_ms=float(slo_p99_ms),
            offered_rate=float(offered_rate),
            fit_samples=self.residual.n_samples)
        return doc

    def repick(self, param_bytes: int, opt_bytes: int, n_shards: int,
               k_candidates: Sequence[int] = (1, 2, 4, 8),
               features: Mapping | None = None,
               hbm_budget: int | None = None,
               batch_bytes: int = 0, activation_bytes: int = 0,
               remat_options: Sequence[str | None] = (None, "full"),
               dtype_options: Sequence[str | None] = (None,),
               ) -> dict:
        """ONE full (plan, K, remat) re-pick for a NEW topology — the
        elastic supervisor's generation-change hook (ISSUE 16).

        A generation change (worker died / rejoined) changes
        ``n_shards``; instead of re-tuning blind, the supervisor asks
        for exactly one :meth:`choose_plan` sweep (plan x remat against
        the HBM budget at the new shard count) plus one
        :meth:`predict_k` (the fused-dispatch prior), so every rejoin
        decision is a logged prediction the round's measured steps/sec
        later scores via :meth:`record_outcome`.  Returns ``{"plan",
        "k", "remat", "config", "doc"}``; ``config`` is the key to
        report the outcome against."""
        feats = features or {}
        plan, doc = self.choose_plan(
            param_bytes, opt_bytes, n_shards, hbm_budget=hbm_budget,
            features=feats, batch_bytes=batch_bytes,
            activation_bytes=activation_bytes,
            remat_options=remat_options, dtype_options=dtype_options)
        k = self.predict_k(feats, k_candidates)
        return {"plan": plan, "k": int(k), "remat": doc["chosen_remat"],
                "dtype": doc["chosen_dtype"],
                "config": doc["chosen_config"], "doc": doc}

    # ------------------------------------------------------------------
    # the outcome half of the data loop
    # ------------------------------------------------------------------
    def record_outcome(self, config: str, measured_steps_per_sec: float,
                       consumer: str = "") -> dict | None:
        """Close a prediction→outcome pair: the consumer reports what
        the config actually measured (the autotuner at K settle, the
        bench per plan leg).  Returns the closed pair (or None when no
        prediction was recorded for ``config`` — outcome still logged,
        error unknowable)."""
        measured = float(measured_steps_per_sec)
        with self._lock:
            pair = self._pairs.get(config)
            if pair is not None:
                pair["measured_steps_per_sec"] = measured
                predicted = pair["predicted_steps_per_sec"]
                pair["rel_error"] = round(
                    abs(predicted - measured) / max(measured, 1e-12), 4)
                pair = dict(pair)
        self.metrics.measured_sps.labels(config=config).set(measured)
        if pair is not None:
            self.metrics.rel_error.labels(config=config).set(
                pair["rel_error"])
        get_flight_recorder().record(
            "oracle", consumer=consumer or "outcome", config=config,
            measured_steps_per_sec=round(measured, 3),
            rel_error=pair["rel_error"] if pair else None)
        return pair

    def _remember_locked(self, record: dict) -> None:
        """Insert/refresh one prediction record under the bounded
        per-config table; called with the lock held."""
        # zoolint: disable=guarded-by -- _locked suffix: callers hold _lock across this call
        self._pairs[record["config"]] = record
        self._pairs.move_to_end(record["config"])
        while len(self._pairs) > self._log_capacity:
            # zoolint: disable=guarded-by -- _locked suffix: callers hold _lock across this call
            self._pairs.popitem(last=False)

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def prediction_log(self) -> list[dict]:
        with self._lock:
            return [dict(p) for p in self._pairs.values()]

    def to_doc(self) -> dict:
        return {
            "peaks": self.peaks.to_doc(),
            "fit_samples": self.residual.n_samples,
            "residual_ready": self.residual.ready,
            "predictions": self.prediction_log(),
        }
