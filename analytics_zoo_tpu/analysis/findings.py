"""Finding model shared by both lint tiers.

A :class:`Finding` is one diagnostic: rule id, severity, location,
message.  Tier 1 (AST) findings carry ``path:line:col``; Tier 2 (HLO)
findings carry the compile label in ``path`` and the op's line within
the lowered module text in ``line``.  Renderers produce the two CLI
output formats (``--format text|json``); both are stable shapes other
tools (pre-commit hooks, CI annotations) can parse.
"""

from __future__ import annotations

import enum
import json
from dataclasses import asdict, dataclass, field


class Severity(enum.IntEnum):
    """Ordered so thresholds compare naturally (INFO < WARNING < ERROR)."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR"
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    """One diagnostic from either tier."""

    rule: str
    severity: Severity
    path: str
    line: int
    message: str
    col: int = 0
    suppressed: bool = False
    #: free-form extras (e.g. the justification text of the suppression
    #: comment, or the lock / attribute names of a concurrency finding)
    data: dict = field(default_factory=dict, compare=False, hash=False)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        d = asdict(self)
        d["severity"] = str(self.severity)
        if not d["data"]:
            d.pop("data")
        return d


def render_text(findings: list[Finding], show_suppressed: bool = False) \
        -> str:
    """One ``path:line:col: severity [rule] message`` line per finding,
    plus a summary tail — the human/CI console format."""
    lines = []
    active = [f for f in findings if not f.suppressed]
    shown = findings if show_suppressed else active
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.col, f.rule)):
        tag = " (suppressed)" if f.suppressed else ""
        lines.append(f"{f.location()}: {f.severity} [{f.rule}] "
                     f"{f.message}{tag}")
    n_sup = len(findings) - len(active)
    lines.append(f"zoolint: {len(active)} finding(s), "
                 f"{n_sup} suppressed")
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    """Machine format: ``{findings: [...], summary: {...}}``."""
    active = [f for f in findings if not f.suppressed]
    doc = {
        "findings": [f.to_dict() for f in sorted(
            findings, key=lambda f: (f.path, f.line, f.col, f.rule))],
        "summary": {
            "total": len(active),
            "suppressed": len(findings) - len(active),
            "by_severity": {
                str(sev): sum(1 for f in active if f.severity == sev)
                for sev in Severity
                if any(f.severity == sev for f in active)
            },
            "by_rule": {
                rule: sum(1 for f in active if f.rule == rule)
                for rule in sorted({f.rule for f in active})
            },
        },
    }
    return json.dumps(doc, indent=2)
