"""Interprocedural concurrency rules (Tier 3 static half, "zoosan").

Consumes the :mod:`callgraph` :class:`~callgraph.Program` and produces
the two whole-program fact families Tier 1 cannot see:

- **Global lock-order graph** (:func:`build_lock_graph`): an edge
  ``A -> B`` means lock ``B`` is acquired somewhere while ``A`` is
  held — *including through calls*: ``f`` holding the registry lock
  and calling a broker method that takes the broker lock contributes
  ``MetricsRegistry._lock -> Broker._cv`` even though no single file
  shows both.  Any cycle is an ABBA deadlock shape and becomes a
  ``lock-order-global`` finding naming both acquisition sites
  (:func:`find_cycles` / the ``test_package_lock_graph_acyclic`` CI
  gate assert acyclicity directly).
- **Guarded-by inference** (:func:`infer_guarded_by`): for every
  instance attribute of a lock-holding class, the lockset under which
  it is written.  An attribute written at least once under a class
  lock but not declared ``# guarded-by:`` is a ``guarded-by-candidate``
  finding — either annotate it (and fix/justify the unlocked writes)
  or suppress with a justification.  A write in a private helper whose
  every resolved call site holds the lock counts as locked (the
  interprocedural fact that retires most Tier-1 false suspicions).

Suppressions use the Tier-1 syntax at the reported line
(``# zoolint: disable=guarded-by-candidate -- why``); the candidate
findings anchor to the attribute's initialising line precisely so the
annotation and the suppression live in the same place.
"""

from __future__ import annotations

import ast
from typing import Iterable

from analytics_zoo_tpu.analysis.callgraph import (
    FunctionInfo,
    Program,
    load_program,
)
from analytics_zoo_tpu.analysis.findings import Finding, Severity
from analytics_zoo_tpu.analysis.rules_concurrency import (
    _EXEMPT_METHODS,
    _self_attr,
)
from analytics_zoo_tpu.analysis.rules_jax import MUTATING_METHODS

__all__ = ["build_lock_graph", "find_cycles", "infer_guarded_by",
           "lint_program", "transitive_acquisitions"]


# ---------------------------------------------------------------------------
# Whole-program lock-order graph.
# ---------------------------------------------------------------------------

def transitive_acquisitions(prog: Program) -> dict:
    """(module, qualname) -> frozenset of lock ids the function may
    acquire, directly or through any resolvable call chain."""
    direct = {info.key: {a.lock_id for a in info.acquisitions}
              for info in prog.iter_functions()}
    callees = {info.key: {c for site in info.calls for c in site.callees}
               for info in prog.iter_functions()}
    acq = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, callee_keys in callees.items():
            mine = acq[key]
            before = len(mine)
            for ck in callee_keys:
                mine |= acq.get(ck, set())
            changed = changed or len(mine) != before
    return {k: frozenset(v) for k, v in acq.items()}


def build_lock_graph(prog: Program) -> dict:
    """``{(outer, inner): (FunctionInfo, lineno, via)}`` — one witness
    site per ordered lock pair; ``via`` is ``"with"`` for a direct
    nested acquisition or the callee qualname for a call-through edge."""
    acq = transitive_acquisitions(prog)
    edges: dict = {}
    for info in prog.iter_functions():
        for a in info.acquisitions:
            for outer in a.held:
                if outer != a.lock_id:
                    edges.setdefault((outer, a.lock_id),
                                     (info, a.node.lineno, "with"))
        for site in info.calls:
            if not site.held:
                continue
            reachable: set = set()
            for ck in site.callees:
                reachable |= acq.get(ck, frozenset())
            for outer in site.held:
                for inner in reachable:
                    if inner != outer:
                        via = site.callees[0][1] if site.callees else "?"
                        edges.setdefault((outer, inner),
                                         (info, site.node.lineno, via))
    return edges


def find_cycles(edges: Iterable) -> list:
    """Minimal cycles in the ordered-pair graph, as sorted lock-id
    tuples (deduplicated by the cycle's node set)."""
    adjacency: dict = {}
    for (a, b) in edges:
        adjacency.setdefault(a, set()).add(b)

    cycles: list = []
    seen: set = set()

    def path_back(start: str, target: str, limit: int = 6):
        """DFS from start back to target, returning one path or None."""
        stack = [(start, (start,))]
        visited = {start}
        while stack:
            node, path = stack.pop()
            if len(path) > limit:
                continue
            for nxt in sorted(adjacency.get(node, ())):
                if nxt == target:
                    return path + (nxt,)
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + (nxt,)))
        return None

    for (a, b) in sorted(edges):
        back = path_back(b, a)
        if back is None:
            continue
        cycle = (a,) + back  # a -> b -> ... -> a
        key = frozenset(cycle)
        if key not in seen:
            seen.add(key)
            cycles.append(cycle)
    return cycles


def _lock_order_findings(prog: Program) -> list:
    edges = build_lock_graph(prog)
    findings = []
    for cycle in find_cycles(edges):
        # the witness for the first edge of the cycle anchors the
        # finding; every edge's site lands in data for the report
        sites = []
        for i in range(len(cycle) - 1):
            pair = (cycle[i], cycle[i + 1])
            if pair in edges:
                info, lineno, via = edges[pair]
                sites.append({"outer": pair[0], "inner": pair[1],
                              "function": f"{info.module}.{info.qualname}",
                              "path": info.mod.path, "line": lineno,
                              "via": via})
        anchor = sites[0] if sites else {"path": "<program>", "line": 0}
        order = " -> ".join(cycle)
        detail = "; ".join(
            f"`{s['inner']}` under `{s['outer']}` in `{s['function']}` "
            f"({s['path']}:{s['line']}"
            + (f", via {s['via']}()" if s.get("via") not in (None, "with")
               else "") + ")"
            for s in sites)
        findings.append(Finding(
            rule="lock-order-global", severity=Severity.ERROR,
            path=anchor["path"], line=anchor["line"],
            message=f"whole-program lock cycle {order}: {detail} — "
            "inconsistent cross-module order can deadlock",
            data={"cycle": list(cycle), "sites": sites}))
    return findings


# ---------------------------------------------------------------------------
# Guarded-by inference.
# ---------------------------------------------------------------------------

def _write_events(info: FunctionInfo):
    """(node, attr) self-attribute write events inside one method —
    assignment / augmented / item write / mutating call / del, own
    scope only (mirrors the Tier-1 rule's write model)."""
    for node in ast.walk(info.node):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            raw = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in raw:
                for leaf in ast.walk(t):
                    attr = _self_attr(leaf)
                    if attr is not None:
                        yield node, attr
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATING_METHODS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                yield node, attr
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                for leaf in ast.walk(t):
                    attr = _self_attr(leaf)
                    if attr is not None:
                        yield node, attr


def _held_class_locks(info: FunctionInfo, node: ast.AST,
                      class_lock_attrs: set) -> set:
    """Class locks held at ``node`` via enclosing ``with self.<lock>``
    statements inside this method."""
    held = set()
    for anc in info.mod.ancestors(node):
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            q = info.mod.qualname(item.context_expr)
            if q and q.startswith("self.") and q[5:] in class_lock_attrs:
                held.add(q[5:])
    return held


def _callers_always_hold(prog: Program, info: FunctionInfo,
                         class_lock_attrs: set) -> set:
    """Locks of ``info``'s class that EVERY resolved call site of
    ``info`` holds — the interprocedural "helper called with the lock
    held" fact.  Only private helpers qualify (a public method must
    lock for itself — today's callers are not a contract), and a
    method with no resolved callers gets nothing."""
    name = info.qualname.rpartition(".")[2]
    if not name.startswith("_") or name.startswith("__"):
        return set()
    prefix = f"{info.module}.{info.cls}."
    held_sets = []
    for other in prog.iter_functions():
        for site in other.calls:
            if info.key not in site.callees:
                continue
            held = {lid.rpartition(".")[2] for lid in site.held
                    if info.cls and lid.startswith(prefix)}
            held_sets.append(held & class_lock_attrs)
    if not held_sets:
        return set()
    out = set(class_lock_attrs)
    for h in held_sets:
        out &= h
    return out


def infer_guarded_by(prog: Program) -> list:
    """``guarded-by-candidate`` findings: lock-holding classes whose
    instance attributes are written under a class lock but carry no
    ``# guarded-by:`` declaration.

    Each finding anchors to the attribute's first write line in
    ``__init__`` (the annotation site).  ``data`` carries the inferred
    lock, the locked/unlocked write counts and every unlocked site, so
    the fix (annotate / fix a race / suppress with a justification) is
    mechanical.
    """
    findings = list(_infer_module_globals(prog))
    for (module, cls), locks in sorted(prog.class_locks.items()):
        lock_attrs = set(locks)
        infos = [f for f in prog.iter_functions()
                 if f.cls == cls and f.module == module]
        if not infos:
            continue
        mod = infos[0].mod
        declared = _declared_attrs(mod, cls)
        init_lines: dict = {}
        locked_writes: dict = {}
        unlocked_writes: dict = {}
        for info in infos:
            exempt = info.qualname.rpartition(".")[2] in _EXEMPT_METHODS
            caller_held = set() if exempt else \
                _callers_always_hold(prog, info, lock_attrs)
            for node, attr in _write_events(info):
                if attr in lock_attrs:
                    continue  # the lock itself
                if exempt:
                    init_lines.setdefault(attr, node.lineno)
                    continue
                held = _held_class_locks(info, node, lock_attrs) \
                    | caller_held
                bucket = locked_writes if held else unlocked_writes
                bucket.setdefault(attr, []).append(
                    (info, node.lineno, sorted(held)))
        for attr in sorted(locked_writes):
            if attr in declared:
                continue  # already annotated
            lock = locked_writes[attr][0][2][0]
            n_locked = len(locked_writes[attr])
            unlocked = unlocked_writes.get(attr, [])
            line = init_lines.get(attr,
                                  locked_writes[attr][0][1])
            where = ", ".join(
                f"{i.qualname} ({i.mod.path}:{ln})"
                for i, ln, _ in unlocked[:4])
            tail = (f"; ALSO written {len(unlocked)}x without it "
                    f"({where}) — fix or justify those sites"
                    if unlocked else "")
            findings.append(Finding(
                rule="guarded-by-candidate", severity=Severity.WARNING,
                path=mod.path, line=line,
                message=f"`{cls}.{attr}` is written {n_locked}x under "
                f"`self.{lock}` but has no `# guarded-by:` annotation "
                f"— declare it so Tier 1 and the runtime sanitizer "
                f"can check every write{tail}",
                data={"cls": cls, "attribute": attr, "lock": lock,
                      "locked_writes": n_locked,
                      "unlocked_writes": [
                          {"method": i.qualname, "path": i.mod.path,
                           "line": ln} for i, ln, _ in unlocked]}))
    return findings


def _infer_module_globals(prog: Program):
    """Module-level analogue: a ``global``-declared name written under
    a module lock wants a ``# guarded-by:`` annotation on its
    module-level initialiser."""
    for module, locks in sorted(prog.module_locks.items()):
        mod = prog.modules[module]
        init_lines: dict = {}
        annotated: set = set()
        for node in mod.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    init_lines.setdefault(t.id, node.lineno)
                    if node.lineno in mod.guarded_by_lines:
                        annotated.add(t.id)
        locked: dict = {}
        for info in [f for f in prog.iter_functions()
                     if f.module == module and f.cls is None]:
            declared = {n for sub in ast.walk(info.node)
                        if isinstance(sub, ast.Global)
                        for n in sub.names}
            if not declared:
                continue
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                         ast.AugAssign)):
                    continue
                raw = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in raw:
                    if not isinstance(t, ast.Name) \
                            or t.id not in declared \
                            or t.id in locks:
                        continue
                    held = [name for name in locks
                            if _module_lock_held(mod, node, name)]
                    if held:
                        locked.setdefault(t.id, (held[0], node.lineno))
        for name in sorted(locked):
            if name in annotated:
                continue
            lock, lineno = locked[name]
            yield Finding(
                rule="guarded-by-candidate", severity=Severity.WARNING,
                path=mod.path, line=init_lines.get(name, lineno),
                message=f"module global `{module}.{name}` is written "
                f"under `{lock}` but has no `# guarded-by:` annotation "
                f"on its initialiser — declare it so Tier 1 checks "
                f"every `global` write",
                data={"module": module, "attribute": name, "lock": lock})


def _module_lock_held(mod, node: ast.AST, lock: str) -> bool:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if mod.qualname(item.context_expr) == lock:
                    return True
    return False


def _declared_attrs(mod, cls_name: str) -> set:
    """Attrs of ``cls_name`` carrying a ``# guarded-by:`` annotation."""
    declared = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)) \
                        and sub.lineno in mod.guarded_by_lines:
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            declared.add(attr)
    return declared


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------

def _apply_program_suppressions(prog: Program,
                                findings: list) -> list:
    """Interprocedural findings honor the same per-line suppression
    comments as Tier 1 (looked up in the module that owns the line)."""
    by_path = {mod.path: mod for mod in prog.modules.values()}
    out = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            rules = mod.suppressed_rules_at(f.line)
            if f.rule in rules or "all" in rules:
                f = Finding(rule=f.rule, severity=f.severity,
                            path=f.path, line=f.line, col=f.col,
                            message=f.message, suppressed=True,
                            data=f.data)
        out.append(f)
    return out


def lint_program(root: str, package: str | None = None,
                 prog: Program | None = None) -> list:
    """The whole-program pass: load (or reuse) the :class:`Program`,
    run cross-module lock-order and guarded-by inference, apply
    suppressions.  This is what ``tools/zoolint.py --whole-program``
    and the ``test_package_is_clean`` gate add on top of Tier 1."""
    prog = prog or load_program(root, package)
    findings = _lock_order_findings(prog) + infer_guarded_by(prog)
    return _apply_program_suppressions(prog, findings)
