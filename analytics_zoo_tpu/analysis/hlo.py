"""Tier-2 HLO graph lint + analytic cost extraction.

Every AOT compile in the repo funnels through
:func:`analytics_zoo_tpu.common.compile_cache.timed_compile`; this
module hooks that choke point to inspect the lowered StableHLO module
TEXT without executing it.  Two outputs per compile:

**Findings** (graph smells that are invisible at runtime):

- ``hlo-f64``: a f64-typed op in a TPU-bound program runs on the slow
  path (or silently upcasts memory traffic 2x) — almost always an
  accidental Python-float promotion;
- ``hlo-host-callback``: a ``custom_call`` to a Python callback forces
  a device->host->device round trip EVERY step;
- ``hlo-all-gather``: an all-gather in a program that only expected
  gradient reductions usually means a sharding mismatch is resharding
  params every dispatch;
- ``hlo-large-constant``: a non-splat constant over the size threshold
  was baked into the graph (a closed-over numpy array) — it bloats the
  executable and the persistent-cache entry, and defeats donation.
- ``hlo-dtype-policy``: the lowered program contradicts the sharding
  plan's DECLARED dtype policy (``ShardingPlan.dtype_rules``, carried
  in compile meta): an f32 matmul under a bf16 compute policy means a
  cast-down never reached that op (it runs at the f32 MXU rate), and a
  bf16/f16 ``all_reduce``/``reduce_scatter`` breaks the
  f32-accumulation contract (gradients must accumulate in f32).  The
  generalization of ``hlo-f64`` from one hardcoded dtype smell to the
  policy the plan actually declared.

**Analytic cost features** (the TpuGraphs direction, arXiv:2308.13490 —
config quality as prediction over the compiled graph; these are the
first inputs of the ROADMAP's cost-model-driven compile plane):

- ``matmul_flops``: 2 * prod(out) * prod(contract) summed over
  ``dot_general``/``dot``/``convolution`` ops — the MXU term, exact
  for dots (hand-countable, pinned by tests);
- ``bytes_accessed``: operand + result bytes summed over ops — the
  HBM-traffic term (approximate: fusion not modelled; ``gather``/
  ``scatter`` charge indices + the touched slices, not the whole
  source tensor);
- ``collective_count`` / ``collective_bytes``: cross-chip traffic
  over ``all_reduce``/``all_gather``/``reduce_scatter``/``all_to_all``/
  ``collective_permute``/``collective_broadcast``; per op the FULL
  participating tensor counts (max of operand/result bytes), so a
  2-device reduce-scatter of a per-device ``tensor<4xf32>`` is 16
  bytes even though each device keeps only half.  Asynchronous
  *paired* forms — ``all_gather_start``/``all_gather_done`` (and the
  XLA-HLO dashed spellings ``all-gather-start``/``-done``, plus async
  ``custom_call`` wrappers) — count ONCE per pair, at the start op;
- ``async_collective_count`` / ``overlapped_collective_bytes``: the
  subset of the collectives above issued as start/done pairs — the
  latency-hiding scheduler's overlappable traffic, the overlap-aware
  roofline's ``exposed_fraction`` numerator;
- ``fused_dispatch_count``: ``stablehlo.while`` ops (one per
  ``lax.scan``/``fori_loop`` — the K-step fused dispatch shape).

Loop bodies and outlined ``func.call`` targets count ONCE: these are
*static graph* features (per-trace), not per-execution totals —
exactly what a cost model over the compiled graph consumes.

Per compile the features land in the ``zoo_hlo_*`` registry metrics
(scrapeable at ``/metrics`` and ``/varz``), in one ``hlo_lint`` flight-
recorder event (a crash dump says what was compiled), in a bounded
in-process last-report-per-label cache (:func:`last_features` — the
config oracle's feature source, deliberately independent of the
metrics registry so predictions work under ``ZOO_METRICS=0``), and —
when ``ZOO_HLO_REPORT_DIR`` is set — in a JSON report file (schema
``zoo-hlo-report/2``: the v1 feature/finding payload plus compile
wall-seconds, sharding-plan label, mesh axis shape, steps_per_dispatch
K and a dtype histogram, so one report row is a self-contained
cost-model training example; readers accept v1 with those fields
null — documented in ``docs/static-analysis.md``).  Disable the whole
tier with ``ZOO_HLO_LINT=0``; the hook never raises into the compile
path.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
from dataclasses import dataclass, field

from analytics_zoo_tpu.analysis.findings import Finding, Severity

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["HloReport", "analyze_hlo_text", "lint_lowered",
           "maybe_lint_lowered", "maybe_write_report", "last_features",
           "DEFAULT_CONSTANT_THRESHOLD"]

#: constants larger than this (bytes) baked into the graph are findings
DEFAULT_CONSTANT_THRESHOLD = 1 << 20

#: collective kinds a data-parallel train step is EXPECTED to contain
#: (a sorted tuple, not a set — its repr appears in generated API docs)
DEFAULT_EXPECTED_COLLECTIVES = (
    "all_reduce", "collective_permute", "reduce_scatter")

_COLLECTIVE_OPS = frozenset(
    {"all_reduce", "all_gather", "reduce_scatter", "all_to_all",
     "collective_permute", "collective_broadcast"})

#: the XLA latency-hiding scheduler splits a collective into a
#: start/done pair; the pair is ONE logical transfer and must count
#: once (at the start op) or the expected-collectives lint misfires
#: twice per overlap-scheduled reduce
_XLA_ASYNC_RE = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)-(start|done)\b")
_ASYNC_CUSTOM_CALL_RE = re.compile(
    r"(all_gather|all_reduce|reduce_scatter|all_to_all|"
    r"collective_permute|collective_broadcast)[\w.]*?_(start|done)\b")


def _split_async_collective(op: str) -> tuple[str, str | None]:
    """``all_gather_start`` -> ``("all_gather", "start")``; a plain
    (synchronous) op comes back with phase ``None``."""
    for phase in ("start", "done"):
        suffix = "_" + phase
        if op.endswith(suffix) and op[:-len(suffix)] in _COLLECTIVE_OPS:
            return op[:-len(suffix)], phase
    return op, None

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1, "i4": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

_OP_RE = re.compile(r'"?stablehlo\.([a-z0-9_]+)"?')
_TENSOR_RE = re.compile(r"tensor<([^<>]*(?:<[^<>]*>)?[^<>]*)>")
_CONTRACT_RE = re.compile(
    r"contracting_dims\s*=\s*\[([0-9,\s]*)\]\s*x\s*\[([0-9,\s]*)\]")
_CUSTOM_CALL_RE = re.compile(r"custom_call\s+@([\w$.]+)")
_KERNEL_OUT_RE = re.compile(r"\]x\[([^\]]*)\]")


@dataclass
class _TensorType:
    dims: tuple
    dtype: str

    @property
    def elements(self) -> int:
        return int(math.prod(self.dims)) if self.dims else 1

    @property
    def nbytes(self) -> int:
        return self.elements * _DTYPE_BYTES.get(self.dtype, 4)


def _parse_tensor(spec: str) -> _TensorType:
    """``8x16xf32`` / ``f32`` / ``1x8x!quant...`` -> dims + dtype."""
    parts = spec.split("x")
    dims, i = [], 0
    while i < len(parts) and parts[i].isdigit():
        dims.append(int(parts[i]))
        i += 1
    dtype = "x".join(parts[i:]) or "f32"
    return _TensorType(tuple(dims), dtype.strip())


def _types_in(text: str) -> list[_TensorType]:
    return [_parse_tensor(m) for m in _TENSOR_RE.findall(text)]


@dataclass
class HloReport:
    """Analytic features + findings for one lowered module."""

    label: str = "module"
    op_count: int = 0
    matmul_flops: int = 0
    bytes_accessed: int = 0
    collective_count: int = 0
    collective_bytes: int = 0
    async_collective_count: int = 0
    overlapped_collective_bytes: int = 0
    fused_dispatch_count: int = 0
    custom_kernel_count: int = 0
    custom_kernel_bytes: int = 0
    collectives: dict = field(default_factory=dict)
    op_histogram: dict = field(default_factory=dict)
    findings: list = field(default_factory=list)
    # schema-v2 context (None/empty when the caller provided none —
    # exactly what a v1 report deserializes to)
    dtype_histogram: dict = field(default_factory=dict)
    compile_seconds: float | None = None
    plan: str | None = None
    mesh_shape: dict | None = None
    steps_per_dispatch: int | None = None
    xla_flags: tuple | None = None
    dtype_policy: str | None = None
    # serving context (ISSUE 20): the pad bucket a predict-labelled
    # program was compiled for — lets the serving cost model key
    # inference_b* rows by bucket without parsing labels
    bucket: int | None = None

    def features(self) -> dict:
        """The flat feature dict exported to metrics / JSON — the cost-
        model input vector."""
        return {
            "op_count": self.op_count,
            "matmul_flops": self.matmul_flops,
            "bytes_accessed": self.bytes_accessed,
            "collective_count": self.collective_count,
            "collective_bytes": self.collective_bytes,
            "async_collective_count": self.async_collective_count,
            "overlapped_collective_bytes":
                self.overlapped_collective_bytes,
            "fused_dispatch_count": self.fused_dispatch_count,
            "custom_kernel_count": self.custom_kernel_count,
            "custom_kernel_bytes": self.custom_kernel_bytes,
        }

    def to_doc(self) -> dict:
        return {
            "schema": "zoo-hlo-report/2",
            "label": self.label,
            "pid": os.getpid(),
            "ts": time.time(),
            "features": self.features(),
            "collectives": dict(self.collectives),
            "op_histogram": dict(self.op_histogram),
            "findings": [f.to_dict() for f in self.findings],
            # v2: the compile/config context that makes one report row
            # a self-contained cost-model training example
            "compile_seconds": self.compile_seconds,
            "plan": self.plan,
            "mesh_shape": dict(self.mesh_shape)
            if self.mesh_shape else None,
            "steps_per_dispatch": self.steps_per_dispatch,
            "xla_flags": list(self.xla_flags) if self.xla_flags
            else None,
            "dtype_histogram": dict(self.dtype_histogram),
            "dtype_policy": self.dtype_policy,
            "bucket": self.bucket,
        }


def _dot_flops(line: str, operands: list, result: list) -> int:
    """2 * prod(out) * prod(lhs contracting dims); exact for dots."""
    if not result:
        return 0
    out_elems = result[0].elements
    m = _CONTRACT_RE.search(line)
    contract = 1
    if m and operands:
        lhs = operands[0]
        for d in m.group(1).split(","):
            d = d.strip()
            if d.isdigit() and int(d) < len(lhs.dims):
                contract *= lhs.dims[int(d)]
    elif operands and operands[0].dims:
        # plain `stablehlo.dot`: contraction over lhs's last dim
        contract = operands[0].dims[-1]
    return 2 * out_elems * contract


def _conv_flops(line: str, operands: list, result: list) -> int:
    """2 * prod(out) * (prod(kernel) / out_channels): approximate —
    grouped/dilated convs are over-counted; exact for the common case."""
    if len(operands) < 2 or not result:
        return 0
    kernel, out = operands[1], result[0]
    k_elems = kernel.elements
    out_ch = 1
    m = _KERNEL_OUT_RE.search(line)
    if m:
        spec = [s.strip() for s in m.group(1).split(",")]
        if "o" in spec and len(kernel.dims) == len(spec):
            out_ch = kernel.dims[spec.index("o")] or 1
    return 2 * out.elements * max(k_elems // max(out_ch, 1), 1)


def _policy_low_precision_roles(dtype_policy) -> set:
    """The low-precision roles a ``<regex>=<role>,...`` policy string
    declares — the lint's activation condition (an empty set = pure-f32
    policy, nothing to check)."""
    roles = set()
    for part in str(dtype_policy or "").split(","):
        if "=" in part:
            roles.add(part.rsplit("=", 1)[1].strip().lower())
    return roles & {"bf16", "f16", "int8"}


def analyze_hlo_text(
        text: str, label: str = "module",
        constant_threshold: int = DEFAULT_CONSTANT_THRESHOLD,
        expected_collectives=DEFAULT_EXPECTED_COLLECTIVES,
        dtype_policy: str | None = None) -> HloReport:
    """Parse a StableHLO module's text into features + findings.

    Line-based: each op contributes its operand/result tensor types
    (from the inline `` : (T...) -> T`` signature, the region-closing
    ``}) : ...`` line, or the single-type elementwise form).  The
    parser is deliberately tolerant — an unrecognised line simply
    contributes nothing.

    ``dtype_policy`` (the plan's ``dtype_policy_str()`` rendering,
    normally forwarded from compile meta) arms the ``hlo-dtype-policy``
    lint when it declares a low-precision role: f32 matmuls and
    bf16/f16 accumulation collectives are flagged against the declared
    contract.  ``None``/pure-f32 policies check nothing — the
    suppressed fixture.
    """
    rpt = HloReport(label=label, dtype_policy=dtype_policy)
    lp_roles = _policy_low_precision_roles(dtype_policy)
    compute_dtypes = sorted(
        {r for r in lp_roles if r in ("bf16", "f16")}
        or ({"bf16"} if lp_roles else set()))
    f64_lines = 0
    f32_matmul_lines = 0
    lp_accum_lines = 0
    # region ops (all_reduce etc.) put their signature on the closing
    # `}) : (...) -> ...` line — remember which op is waiting for it
    pending: list[tuple[str, str]] = []  # (op, original line)

    def account(op: str, line: str, lineno: int):
        sig = line.rpartition(" : ")[2].strip() if " : " in line else ""
        operands: list[_TensorType] = []
        results: list[_TensorType] = []
        if sig.startswith("("):
            depth, split_at = 0, len(sig)
            for i, ch in enumerate(sig):
                depth += ch == "("
                depth -= ch == ")"
                if depth == 0:
                    split_at = i
                    break
            operands = _types_in(sig[:split_at + 1])
            results = _types_in(sig[split_at + 1:])
        elif sig:
            # single-type form: `add %a, %b : tensor<8xf32>` (operands
            # and result share the type) or the while header's
            # comma-list of carried types
            results = _types_in(sig)
            if op not in ("while", "constant", "return", "iota"):
                operands = list(results)

        rpt.op_count += 1
        rpt.op_histogram[op] = rpt.op_histogram.get(op, 0) + 1
        for t in operands + results:
            rpt.dtype_histogram[t.dtype] = \
                rpt.dtype_histogram.get(t.dtype, 0) + 1
        if op == "gather" and len(operands) >= 2 and results:
            # a gather reads the index vector and the GATHERED SLICES
            # (result-sized), not the whole operand — charging the full
            # source tensor would make an embedding lookup look like a
            # full-table scan to the cost model
            rpt.bytes_accessed += operands[1].nbytes \
                + 2 * results[0].nbytes
        elif op == "scatter" and len(operands) >= 3:
            # symmetric: indices + updates read + the updated positions
            # written (XLA aliases the untouched region)
            rpt.bytes_accessed += operands[1].nbytes \
                + 2 * operands[2].nbytes
        else:
            rpt.bytes_accessed += sum(t.nbytes for t in operands) + \
                sum(t.nbytes for t in results)

        if op in ("dot_general", "dot"):
            rpt.matmul_flops += _dot_flops(line, operands, results)
        elif op == "convolution":
            rpt.matmul_flops += _conv_flops(line, operands, results)
        elif op == "while":
            rpt.fused_dispatch_count += 1
        elif _split_async_collective(op)[0] in _COLLECTIVE_OPS:
            base, phase = _split_async_collective(op)
            if phase != "done":
                # a start/done pair is ONE logical transfer: count it at
                # the start op, skip the done op entirely (counting both
                # would double traffic and fire the expected-collectives
                # lint twice per overlap-scheduled reduce)
                rpt.collective_count += 1
                rpt.collectives[base] = rpt.collectives.get(base, 0) + 1
                # the FULL participating tensor moves over the
                # interconnect: for all_reduce operand == result, for
                # reduce_scatter the operand is N× the (scattered)
                # result, for all_gather the result is N× the operand —
                # max() covers all three shapes
                moved = max(
                    sum(t.nbytes for t in operands),
                    sum(t.nbytes for t in results))
                rpt.collective_bytes += moved
                if phase == "start":
                    rpt.async_collective_count += 1
                    rpt.overlapped_collective_bytes += moved
                if base not in expected_collectives:
                    rpt.findings.append(Finding(
                        rule="hlo-all-gather" if "gather" in base
                        else "hlo-collective", severity=Severity.WARNING,
                        path=label, line=lineno,
                        message=f"unexpected `{op}` in the graph — in a "
                        "data-parallel step this usually means a "
                        "sharding mismatch is regathering state every "
                        "dispatch", data={"op": op, "base": base}))
        elif op == "custom_call":
            m = _CUSTOM_CALL_RE.search(line)
            target = m.group(1) if m else "?"
            am = _ASYNC_CUSTOM_CALL_RE.search(target)
            if am:
                # async wrapper spelled as a custom_call (some backends
                # lower latency-hiding collectives this way) — same
                # pair-counts-once rule keyed on the target name
                base = am.group(1)
                if am.group(2) == "start":
                    moved = max(
                        sum(t.nbytes for t in operands),
                        sum(t.nbytes for t in results))
                    rpt.collective_count += 1
                    rpt.collectives[base] = \
                        rpt.collectives.get(base, 0) + 1
                    rpt.collective_bytes += moved
                    rpt.async_collective_count += 1
                    rpt.overlapped_collective_bytes += moved
                    if base not in expected_collectives:
                        rpt.findings.append(Finding(
                            rule="hlo-all-gather" if "gather" in base
                            else "hlo-collective",
                            severity=Severity.WARNING,
                            path=label, line=lineno,
                            message=f"unexpected async `{target}` in "
                            "the graph — in a data-parallel step this "
                            "usually means a sharding mismatch is "
                            "regathering state every dispatch",
                            data={"target": target, "base": base}))
            elif target == "tpu_custom_call" \
                    or "mosaic" in target.lower():
                # a Pallas/Mosaic kernel: attribute its operand+result
                # bytes to the label so kernel-vs-XLA A/Bs can compare
                # measured bytes-accessed against the cost model's
                # per-kernel prediction (bytes_accessed above already
                # counts them; this is the per-kernel slice)
                rpt.custom_kernel_count += 1
                rpt.custom_kernel_bytes += \
                    sum(t.nbytes for t in operands) + \
                    sum(t.nbytes for t in results)
            elif re.search(r"callback|python|py_", target,
                           re.IGNORECASE):
                rpt.findings.append(Finding(
                    rule="hlo-host-callback", severity=Severity.WARNING,
                    path=label, line=lineno,
                    message=f"host callback `{target}` baked into the "
                    "graph — every dispatch pays a device->host->device "
                    "round trip", data={"target": target}))
        elif op == "constant" and results:
            splat = re.search(r"dense<[^\[\"]", line) is not None
            size = results[0].nbytes
            if not splat and size > constant_threshold:
                rpt.findings.append(Finding(
                    rule="hlo-large-constant", severity=Severity.WARNING,
                    path=label, line=lineno,
                    message=f"{size} byte constant baked into the graph "
                    "(a closed-over host array?) — pass it as an "
                    "argument so it is not re-serialized per executable",
                    data={"bytes": size}))

        nonlocal f32_matmul_lines, lp_accum_lines
        if lp_roles:
            if op in ("dot_general", "dot", "convolution") and any(
                    t.dtype == "f32" for t in operands):
                f32_matmul_lines += 1
                if f32_matmul_lines == 1:
                    rpt.findings.append(Finding(
                        rule="hlo-dtype-policy",
                        severity=Severity.WARNING,
                        path=label, line=lineno,
                        message=f"f32 `{op}` under a "
                        f"{'/'.join(compute_dtypes)} compute policy "
                        "(first of several?) — the cast-down never "
                        "reached this op, so it runs at the f32 MXU "
                        "rate", data={"op": op, "dtype": "f32"}))
            base, phase = _split_async_collective(op)
            if base in ("all_reduce", "reduce_scatter") \
                    and phase != "done" \
                    and any(t.dtype in ("bf16", "f16")
                            for t in operands + results):
                lp_accum_lines += 1
                if lp_accum_lines == 1:
                    rpt.findings.append(Finding(
                        rule="hlo-dtype-policy",
                        severity=Severity.WARNING,
                        path=label, line=lineno,
                        message=f"low-precision `{op}` breaks the "
                        "f32-accumulation contract — gradients must "
                        "accumulate in f32 (cast up BEFORE the "
                        "collective, not after)",
                        data={"op": op, "base": base}))

        nonlocal f64_lines
        if any(t.dtype == "f64" for t in operands + results):
            f64_lines += 1
            if f64_lines == 1:
                rpt.findings.append(Finding(
                    rule="hlo-f64", severity=Severity.WARNING,
                    path=label, line=lineno,
                    message="f64 op in the graph (first of several?) — "
                    "TPUs emulate f64 at a fraction of f32 throughput; "
                    "an accidental Python-float promotion is the usual "
                    "cause", data={"op": op}))

    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if pending and line.startswith("})") and " : " in line:
            op, op_line = pending.pop()
            account(op, op_line + " " + line, lineno)
            continue
        m = _OP_RE.search(line)
        if not m:
            # post-optimization XLA HLO spells async pairs with dashes
            # (`all-gather-start` / `all-gather-done`) and no stablehlo.
            # prefix — normalize to the underscore pair form
            am = _XLA_ASYNC_RE.search(line)
            if am:
                account(am.group(1).replace("-", "_") + "_"
                        + am.group(2), line, lineno)
            continue
        op = m.group(1)
        if op == "return":
            continue
        if line.endswith("({") or line.endswith("{") and " : " not in line:
            # region op, signature comes on the closing line
            pending.append((op, line))
            continue
        account(op, line, lineno)

    if f64_lines > 1:
        # summarize instead of one finding per op — the first finding
        # carries the location, this one the magnitude
        rpt.findings.append(Finding(
            rule="hlo-f64", severity=Severity.WARNING, path=label, line=0,
            message=f"{f64_lines} f64-typed ops total in this module",
            data={"count": f64_lines}))
    if f32_matmul_lines > 1:
        rpt.findings.append(Finding(
            rule="hlo-dtype-policy", severity=Severity.WARNING,
            path=label, line=0,
            message=f"{f32_matmul_lines} f32 matmul ops total under a "
            f"{'/'.join(compute_dtypes)} compute policy",
            data={"count": f32_matmul_lines, "kind": "f32-matmul"}))
    if lp_accum_lines > 1:
        rpt.findings.append(Finding(
            rule="hlo-dtype-policy", severity=Severity.WARNING,
            path=label, line=0,
            message=f"{lp_accum_lines} low-precision accumulation "
            "collectives total in this module",
            data={"count": lp_accum_lines, "kind": "lp-accum"}))
    return rpt


# ---------------------------------------------------------------------------
# The timed_compile hook: metrics + flight record + JSON report.
# ---------------------------------------------------------------------------

_report_seq = 0  # guarded-by: _report_lock
_report_lock = threading.Lock()

# Bounded last-report-per-label cache: the config oracle's feature
# source.  Deliberately NOT the metrics registry — zoo_hlo_* gauges are
# NULL children under ZOO_METRICS=0, and the oracle must still see the
# compiled program's features then.
_LAST_REPORTS_CAP = 64
_last_lock = threading.Lock()
_last_reports: dict = {}  # guarded-by: _last_lock  (label -> HloReport)


def remember_report(rpt: HloReport) -> None:
    """Retain ``rpt`` as the latest report for its label (bounded:
    oldest label evicted past :data:`_LAST_REPORTS_CAP`)."""
    with _last_lock:
        _last_reports.pop(rpt.label, None)  # re-insert = move to end
        _last_reports[rpt.label] = rpt
        while len(_last_reports) > _LAST_REPORTS_CAP:
            del _last_reports[next(iter(_last_reports))]


def last_features(label: str) -> dict | None:
    """The feature vector of the most recent compile under ``label``
    (None when nothing compiled under it yet in this process)."""
    with _last_lock:
        rpt = _last_reports.get(label)
    return rpt.features() if rpt is not None else None


def _emit_metrics(rpt: HloReport) -> None:
    from analytics_zoo_tpu.metrics import get_registry

    reg = get_registry()
    gauges = {
        "zoo_hlo_flops":
            ("analytic matmul (MXU) FLOPs of the lowered module",
             rpt.matmul_flops),
        "zoo_hlo_bytes_accessed":
            ("analytic operand+result bytes touched by the lowered "
             "module (fusion not modelled)", rpt.bytes_accessed),
        "zoo_hlo_collectives":
            ("collective ops in the lowered module",
             rpt.collective_count),
        "zoo_hlo_collective_bytes":
            ("bytes moved by collective ops in the lowered module",
             rpt.collective_bytes),
        "zoo_hlo_async_collectives":
            ("async start/done collective pairs in the lowered module "
             "(each pair counts once)", rpt.async_collective_count),
        "zoo_hlo_overlapped_collective_bytes":
            ("bytes moved by async (overlappable) collective pairs in "
             "the lowered module", rpt.overlapped_collective_bytes),
        "zoo_hlo_fused_dispatches":
            ("while loops (lax.scan / fori_loop) in the lowered module",
             rpt.fused_dispatch_count),
        "zoo_hlo_custom_kernels":
            ("Pallas/Mosaic custom_call kernels in the lowered module",
             rpt.custom_kernel_count),
        "zoo_hlo_custom_kernel_bytes":
            ("operand+result bytes of Pallas/Mosaic custom_call "
             "kernels in the lowered module", rpt.custom_kernel_bytes),
        "zoo_hlo_ops":
            ("total StableHLO ops in the lowered module", rpt.op_count),
        "zoo_hlo_findings":
            ("HLO lint findings for the lowered module",
             len(rpt.findings)),
    }
    for name, (help_, value) in gauges.items():
        reg.gauge(name, help_, ("label",)).labels(
            label=rpt.label).set(value)
    counter = reg.counter("zoo_hlo_lint_findings_total",
                          "HLO lint findings by rule", ("rule",))
    for f in rpt.findings:
        counter.labels(rule=f.rule).inc()


def _write_report(rpt: HloReport, report_dir: str) -> str | None:
    global _report_seq
    with _report_lock:
        _report_seq += 1
        seq = _report_seq
    safe = re.sub(r"[^\w.-]", "_", rpt.label)
    path = os.path.join(report_dir,
                        f"hlo-{safe}-{os.getpid()}-{seq}.json")
    try:
        os.makedirs(report_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rpt.to_doc(), f, indent=2)
        os.replace(tmp, path)
    except OSError:
        return None  # reports are best-effort; never fail the compile
    return path


def lint_lowered(lowered, label: str = "module",
                 report_dir: str | None = None,
                 meta: dict | None = None,
                 defer_report: bool = False) -> HloReport:
    """Analyze a ``jax.jit(f).lower(...)`` result: findings + features
    into metrics, the flight recorder and (optionally) a JSON report.

    ``report_dir`` defaults to ``ZOO_HLO_REPORT_DIR``; pass a path to
    force a report, or rely on the env knob.  ``meta`` carries the
    schema-v2 compile context the lowered text cannot show (``plan``,
    ``mesh_shape``, ``steps_per_dispatch``, ``dtype_policy`` — the
    plan's declared precision contract, which arms the
    ``hlo-dtype-policy`` lint; an optional
    ``expected_collectives`` widens the collective lint's allow-list
    for graphs that gather by design).  ``defer_report=True``
    skips the report write — :func:`timed_compile` uses it to lint
    BEFORE compiling (the crash-dump contract: the flight ring must say
    what was being compiled if the compile dies) and write the report
    AFTER via :func:`maybe_write_report`, once the compile
    wall-seconds exist.
    """
    text = lowered.as_text()
    expected = DEFAULT_EXPECTED_COLLECTIVES
    if meta and meta.get("expected_collectives"):
        # the caller KNOWS its graph gathers (zero3 / fsdp prefetch
        # regather parameters by design) — widening the expected set
        # here beats suppressing the finding after the fact
        expected = tuple(meta["expected_collectives"])
    # the plan's declared precision contract, stamped into compile meta
    # by compile_step — arms the hlo-dtype-policy lint
    dtype_policy = meta.get("dtype_policy") if meta else None
    rpt = analyze_hlo_text(text, label=label,
                           expected_collectives=expected,
                           dtype_policy=dtype_policy)
    for key in ("plan", "mesh_shape", "steps_per_dispatch",
                "xla_flags", "bucket"):
        if meta and meta.get(key) is not None:
            setattr(rpt, key, meta[key])
    remember_report(rpt)
    _emit_metrics(rpt)

    from analytics_zoo_tpu.metrics import get_flight_recorder

    # the crash-dump answer to "what was compiled?": one event per
    # compile with the feature vector and the lint verdict
    get_flight_recorder().record(
        "hlo_lint", label=label, **rpt.features(),
        findings=[f.rule for f in rpt.findings])

    for f in rpt.findings:
        logger.warning("hlo-lint[%s]: %s (%s)", label, f.message, f.rule)

    if not defer_report:
        report_dir = report_dir or os.environ.get("ZOO_HLO_REPORT_DIR")
        if report_dir:
            _write_report(rpt, report_dir)
    return rpt


def maybe_write_report(rpt: HloReport | None,
                       compile_seconds: float | None = None,
                       report_dir: str | None = None) -> str | None:
    """The deferred second half of a ``defer_report=True`` lint: stamp
    the measured compile wall-seconds onto the report and write it if
    ``ZOO_HLO_REPORT_DIR`` (or ``report_dir``) asks for one.  Safe on
    None (lint disabled/failed) and never raises."""
    if rpt is None:
        return None
    try:
        if compile_seconds is not None:
            rpt.compile_seconds = float(compile_seconds)
        report_dir = report_dir or os.environ.get("ZOO_HLO_REPORT_DIR")
        if report_dir:
            return _write_report(rpt, report_dir)
    except Exception:  # reports are best-effort, like the lint itself
        logger.debug("hlo report write failed for %s", rpt.label,
                     exc_info=True)
    return None


def maybe_lint_lowered(lowered, label: str = "module",
                       meta: dict | None = None,
                       defer_report: bool = False) -> HloReport | None:
    """The guarded entry :func:`timed_compile` calls: no-op under
    ``ZOO_HLO_LINT=0``, and NEVER raises into the compile path."""
    if os.environ.get("ZOO_HLO_LINT", "1") == "0":
        return None
    try:
        return lint_lowered(lowered, label, meta=meta,
                            defer_report=defer_report)
    except Exception:  # the lint must never take a compile down
        logger.debug("hlo lint failed for %s", label, exc_info=True)
        return None
