"""Concurrency pitfall rules (Tier 1).

The threaded planes (prefetch producer pool, pipelined serving
reader/writer, infeed feeder, metrics HTTP server, heartbeats) share
mutable state whose locking discipline pytest cannot check — a lost
write needs the right interleaving; a deadlock needs the wrong one.
These rules make the discipline declarative and machine-checked:

- ``guarded-by``: an attribute initialised with a ``# guarded-by:
  <lock>`` comment may only be WRITTEN (assignment, augmented
  assignment, item write, mutating method call) inside ``with
  self.<lock>:``.  ``__init__``/``__post_init__`` are exempt (the
  object is not yet shared), as is the annotated declaration line
  itself.  Module GLOBALS work the same way: an annotated module-level
  assignment (``_CONTEXT = None  # guarded-by: _LOCK``) makes every
  ``global``-declared write require ``with _LOCK:`` (module-level
  initialisation is exempt).  Reads are deliberately unchecked — the
  codebase uses intentional lock-free reads (double-checked creation,
  monotonic snapshots); checking them would bury the real signal.
- ``lock-order``: two locks nested in opposite orders in different
  functions is the classic ABBA deadlock.  Lock-looking context
  managers (``with self._lock:`` where the name contains "lock") are
  tracked per module; the pair graph must stay acyclic.
- ``bare-except``: a bare ``except:`` swallows ``SystemExit`` /
  ``KeyboardInterrupt``; on a daemon thread it turns a crash into a
  silent wedge the health model then has to catch at the /healthz
  level.  Handlers that re-raise are allowed.
"""

from __future__ import annotations

import ast
from typing import Iterator

from analytics_zoo_tpu.analysis.astlint import LintModule, Rule
from analytics_zoo_tpu.analysis.findings import Finding, Severity
from analytics_zoo_tpu.analysis.rules_jax import MUTATING_METHODS

__all__ = ["CONCURRENCY_RULES", "GuardedByRule", "LockOrderRule",
           "BareExceptRule"]

_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "__del__"}


def _self_attr(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _root_self_attr(node: ast.AST) -> str | None:
    """The self-attribute at the root of an expression chain:
    ``self._q[...]`` / ``self._q.items`` -> ``_q``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        attr = _self_attr(node)
        if attr is not None:
            return attr
        node = node.value
    return None


class GuardedByRule(Rule):
    name = "guarded-by"
    severity = Severity.ERROR
    description = ("write to a `# guarded-by: <lock>` attribute without "
                   "the lock held")

    def _declared_guards(self, mod: LintModule,
                         cls: ast.ClassDef) -> dict[str, str]:
        """{attr: lock} from annotated initialising assignments."""
        guards: dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            lock = mod.guarded_by_lines.get(node.lineno)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    guards[attr] = lock
        return guards

    @staticmethod
    def _lock_held(mod: LintModule, node: ast.AST, lock: str) -> bool:
        for anc in mod.ancestors(node):
            if not isinstance(anc, (ast.With, ast.AsyncWith)):
                continue
            for item in anc.items:
                q = mod.qualname(item.context_expr)
                if q in (f"self.{lock}", lock):
                    return True
        return False

    @staticmethod
    def _flatten_targets(t) -> Iterator[ast.AST]:
        """Expand tuple/list/starred assignment targets to their leaves
        (``self._a, x = ...`` writes self._a just as surely)."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from GuardedByRule._flatten_targets(e)
        elif isinstance(t, ast.Starred):
            yield from GuardedByRule._flatten_targets(t.value)
        else:
            yield t

    def _writes(self, method) -> Iterator[tuple]:
        """(node, attr, how) write events against self attributes."""
        for node in ast.walk(method):
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                raw = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                targets = [leaf for t in raw
                           for leaf in self._flatten_targets(t)]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        yield node, attr, "assignment"
                        continue
                    if isinstance(t, ast.Subscript):
                        attr = _root_self_attr(t)
                        if attr is not None:
                            yield node, attr, "item assignment"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATING_METHODS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    yield node, attr, f".{node.func.attr}() call"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _root_self_attr(t)
                    if attr is not None:
                        yield node, attr, "del"

    def _module_guards(self, mod: LintModule) -> dict[str, str]:
        """{global name: lock} from annotated MODULE-LEVEL assignments
        (statements whose enclosing scope is the module itself)."""
        guards: dict[str, str] = {}
        for node in mod.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            lock = mod.guarded_by_lines.get(node.lineno)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    guards[t.id] = lock
        return guards

    def _check_module_globals(self, mod: LintModule) -> Iterator[Finding]:
        guards = self._module_guards(mod)
        if not guards:
            return
        for fn in mod.functions():
            declared = {n for node in ast.walk(fn)
                        if isinstance(node, ast.Global)
                        for n in node.names}
            watched = declared & set(guards)
            if not watched:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    raw = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    targets = [leaf for t in raw
                               for leaf in self._flatten_targets(t)]
                    for t in targets:
                        if not isinstance(t, ast.Name) \
                                or t.id not in watched:
                            continue
                        lock = guards[t.id]
                        if not self._lock_held(mod, node, lock):
                            yield self.finding(
                                mod, node,
                                f"assignment to module global "
                                f"`{t.id}` (guarded-by `{lock}`) in "
                                f"`{fn.name}` without `with {lock}:` "
                                f"held",
                                attribute=t.id, lock=lock,
                                method=fn.name)

    def check(self, mod: LintModule) -> Iterator[Finding]:
        yield from self._check_module_globals(mod)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = self._declared_guards(mod, cls)
            if not guards:
                continue
            declared_lines = {ln for ln in mod.guarded_by_lines}
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)) \
                        or method.name in _EXEMPT_METHODS:
                    continue
                for node, attr, how in self._writes(method):
                    lock = guards.get(attr)
                    if lock is None or node.lineno in declared_lines:
                        continue
                    if not self._lock_held(mod, node, lock):
                        yield self.finding(
                            mod, node,
                            f"{how} to `self.{attr}` (guarded-by "
                            f"`{lock}`) in `{cls.name}.{method.name}` "
                            f"without `with self.{lock}:` held",
                            attribute=attr, lock=lock,
                            method=f"{cls.name}.{method.name}")


class LockOrderRule(Rule):
    name = "lock-order"
    severity = Severity.WARNING
    description = ("locks acquired in opposite nesting orders in "
                   "different functions (ABBA deadlock shape)")

    @staticmethod
    def _lock_id(mod: LintModule, cls_name: str | None,
                 expr: ast.AST) -> str | None:
        q = mod.qualname(expr)
        if q is None:
            return None
        base = q.rsplit(".", 1)[-1]
        if "lock" not in base.lower():
            return None
        if q.startswith("self."):
            return f"{cls_name or '?'}.{q[5:]}"
        return q

    def check(self, mod: LintModule) -> Iterator[Finding]:
        # pair (outer, inner) -> (node of inner acquisition, fn name)
        pairs: dict[tuple, tuple] = {}

        def enclosing_class(fn) -> str | None:
            for anc in mod.ancestors(fn):
                if isinstance(anc, ast.ClassDef):
                    return anc.name
            return None

        def walk(node, held: tuple, cls_name, fn_name):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(mod, cls_name, item.context_expr)
                    if lid is not None:
                        for h in held:
                            pairs.setdefault((h, lid), (node, fn_name))
                        held = held + (lid,)
                for child in node.body:
                    walk(child, held, cls_name, fn_name)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held, cls_name, fn_name)

        for fn in mod.functions():
            cls_name = enclosing_class(fn)
            for stmt in fn.body:
                walk(stmt, (), cls_name, fn.name)

        reported = set()
        for (a, b), (node, fn_name) in sorted(
                pairs.items(), key=lambda kv: kv[1][0].lineno):
            if (b, a) in pairs and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                other_node, other_fn = pairs[(b, a)]
                yield self.finding(
                    mod, node,
                    f"lock `{b}` acquired under `{a}` in `{fn_name}` "
                    f"but `{a}` is acquired under `{b}` in "
                    f"`{other_fn}` (line {other_node.lineno}) — "
                    "inconsistent order can deadlock",
                    locks=[a, b], other_line=other_node.lineno)


class BareExceptRule(Rule):
    name = "bare-except"
    severity = Severity.WARNING
    description = ("bare `except:` swallows SystemExit/KeyboardInterrupt "
                   "— on a daemon thread it wedges silently")

    def check(self, mod: LintModule) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                reraises = any(isinstance(n, ast.Raise)
                               for b in node.body for n in ast.walk(b))
                if not reraises:
                    yield self.finding(
                        mod, node,
                        "bare `except:` swallows SystemExit and "
                        "KeyboardInterrupt — a daemon thread dies into "
                        "a silent wedge; catch `Exception` (or "
                        "re-raise)")


CONCURRENCY_RULES = (GuardedByRule(), LockOrderRule(), BareExceptRule())
