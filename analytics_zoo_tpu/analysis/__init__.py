"""``analytics_zoo_tpu.analysis`` — two-tier static analysis (zoolint).

The codebase is heavily threaded (prefetch producer pool, pipelined
serving reader/writer, infeed feeder, metrics HTTP server) and heavily
jitted (fused ``lax.scan`` dispatch, per-bucket inference compiles).
Its two dominant failure classes — silent host/device performance
hazards inside traced code, and data races on shared mutable state —
are invisible to pytest: a side effect traced into a jit runs once at
trace time and never again, and a missing lock loses a write only under
the right interleaving.  This package makes both *compile-time* errors:

**Tier 1 — AST lint ("zoolint")**: a rule engine over Python ASTs
(:mod:`astlint`) with file:line findings, severities and
``# zoolint: disable=<rule>`` suppressions.  JAX rules
(:mod:`rules_jax`): Python side effects inside jit/scan-traced
functions, PRNG key reuse without ``split``/``fold_in``, host syncs on
annotated hot paths, non-donated training carries.  Concurrency rules
(:mod:`rules_concurrency`): writes to ``# guarded-by: <lock>``
attributes without the lock held, inconsistent lock acquisition order,
bare ``except:`` that swallows exceptions in daemon threads.  The CLI
is ``tools/zoolint.py`` (``--format text|json``, nonzero exit on
findings) and the quick-tier gate
``tests/test_zoolint.py::test_package_is_clean`` keeps the package at
zero unsuppressed findings.

**Tier 2 — HLO graph lint + analytic cost extraction** (:mod:`hlo`):
every AOT compile routed through
:func:`analytics_zoo_tpu.common.compile_cache.timed_compile` has its
lowered StableHLO module text inspected WITHOUT executing it — f64 ops,
host callbacks, unexpected all-gathers and oversized baked-in constants
become findings; analytic cost features (matmul FLOPs, bytes touched,
collective count/bytes, fused-dispatch count) land in the
``zoo_hlo_*`` registry metrics, a per-compile JSON report
(``ZOO_HLO_REPORT_DIR``) and the crash flight recorder.  These are the
graph features the ROADMAP's cost-model-driven compile plane
(TpuGraphs, arXiv:2308.13490) consumes: config quality as prediction
over the compiled graph, extracted for free at the compile choke point.

**Tier 3 — whole-program + runtime sanitizer ("zoosan")**: the static
half (:mod:`callgraph` + :mod:`rules_interproc`) links every file into
one symbol table and call graph so lock-order cycles are found ACROSS
modules and un-annotated lock-guarded attributes become
``guarded-by-candidate`` findings; the dynamic half (:mod:`sanitizer`,
``ZOO_SAN=1``) wraps the package's locks at creation time and proves
the annotations at runtime — lockdep cycle detection with both stacks,
``# guarded-by`` writes validated against the live lock owner, and
blocking calls under a held lock flagged.  Zero cost when disabled:
with ``ZOO_SAN`` unset nothing is patched.

See ``docs/static-analysis.md`` for the rule catalogue, suppression
syntax, the ``# guarded-by:`` annotation convention and the HLO report
schema.
"""

from analytics_zoo_tpu.analysis.findings import (
    Finding,
    Severity,
    render_json,
    render_text,
)
from analytics_zoo_tpu.analysis.astlint import (
    ALL_RULES,
    LintModule,
    Rule,
    lint_file,
    lint_paths,
    lint_source,
)

__all__ = [
    "Finding", "Severity", "render_text", "render_json",
    "Rule", "LintModule", "ALL_RULES",
    "lint_source", "lint_file", "lint_paths",
    "HloReport", "analyze_hlo_text", "lint_lowered",
    "load_program", "lint_program", "build_lock_graph", "find_cycles",
    "ConfigOracle", "ResidualModel", "PeakTable", "resolve_peaks",
    "predict_steps_per_sec",
]

# The HLO tier and the whole-program pass load lazily (PEP 562): the
# package __init__ imports this module BEFORE the sanitizer can patch
# threading, and an eager `hlo` import would allocate its report lock
# too early for the sanitizer to wrap (it would also drag the parser
# into every `import analytics_zoo_tpu`).
_LAZY = {
    "HloReport": "hlo", "analyze_hlo_text": "hlo", "lint_lowered": "hlo",
    "load_program": "callgraph",
    "lint_program": "rules_interproc",
    "build_lock_graph": "rules_interproc",
    "find_cycles": "rules_interproc",
    "ConfigOracle": "oracle",
    "ResidualModel": "costmodel", "PeakTable": "costmodel",
    "resolve_peaks": "costmodel", "predict_steps_per_sec": "costmodel",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(
            f"analytics_zoo_tpu.analysis.{_LAZY[name]}")
        value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
