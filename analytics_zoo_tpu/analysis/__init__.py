"""``analytics_zoo_tpu.analysis`` — two-tier static analysis (zoolint).

The codebase is heavily threaded (prefetch producer pool, pipelined
serving reader/writer, infeed feeder, metrics HTTP server) and heavily
jitted (fused ``lax.scan`` dispatch, per-bucket inference compiles).
Its two dominant failure classes — silent host/device performance
hazards inside traced code, and data races on shared mutable state —
are invisible to pytest: a side effect traced into a jit runs once at
trace time and never again, and a missing lock loses a write only under
the right interleaving.  This package makes both *compile-time* errors:

**Tier 1 — AST lint ("zoolint")**: a rule engine over Python ASTs
(:mod:`astlint`) with file:line findings, severities and
``# zoolint: disable=<rule>`` suppressions.  JAX rules
(:mod:`rules_jax`): Python side effects inside jit/scan-traced
functions, PRNG key reuse without ``split``/``fold_in``, host syncs on
annotated hot paths, non-donated training carries.  Concurrency rules
(:mod:`rules_concurrency`): writes to ``# guarded-by: <lock>``
attributes without the lock held, inconsistent lock acquisition order,
bare ``except:`` that swallows exceptions in daemon threads.  The CLI
is ``tools/zoolint.py`` (``--format text|json``, nonzero exit on
findings) and the quick-tier gate
``tests/test_zoolint.py::test_package_is_clean`` keeps the package at
zero unsuppressed findings.

**Tier 2 — HLO graph lint + analytic cost extraction** (:mod:`hlo`):
every AOT compile routed through
:func:`analytics_zoo_tpu.common.compile_cache.timed_compile` has its
lowered StableHLO module text inspected WITHOUT executing it — f64 ops,
host callbacks, unexpected all-gathers and oversized baked-in constants
become findings; analytic cost features (matmul FLOPs, bytes touched,
collective count/bytes, fused-dispatch count) land in the
``zoo_hlo_*`` registry metrics, a per-compile JSON report
(``ZOO_HLO_REPORT_DIR``) and the crash flight recorder.  These are the
graph features the ROADMAP's cost-model-driven compile plane
(TpuGraphs, arXiv:2308.13490) consumes: config quality as prediction
over the compiled graph, extracted for free at the compile choke point.

See ``docs/static-analysis.md`` for the rule catalogue, suppression
syntax, the ``# guarded-by:`` annotation convention and the HLO report
schema.
"""

from analytics_zoo_tpu.analysis.findings import (
    Finding,
    Severity,
    render_json,
    render_text,
)
from analytics_zoo_tpu.analysis.astlint import (
    ALL_RULES,
    LintModule,
    Rule,
    lint_file,
    lint_paths,
    lint_source,
)
from analytics_zoo_tpu.analysis.hlo import (
    HloReport,
    analyze_hlo_text,
    lint_lowered,
)

__all__ = [
    "Finding", "Severity", "render_text", "render_json",
    "Rule", "LintModule", "ALL_RULES",
    "lint_source", "lint_file", "lint_paths",
    "HloReport", "analyze_hlo_text", "lint_lowered",
]
