"""Tier-1 rule engine — Python AST lint with suppressions ("zoolint").

The engine parses each file once into a :class:`LintModule` (AST +
comment map + import-alias table + the traced-function set) and hands
it to every registered :class:`Rule`; findings carry ``path:line:col``
and are marked suppressed when a ``# zoolint: disable=<rule>`` comment
covers their line.  The rule catalogue lives in :mod:`rules_jax` and
:mod:`rules_concurrency`; ``docs/static-analysis.md`` documents every
rule and the annotation conventions.

Suppression syntax (checked per line):

- ``# zoolint: disable=rule1,rule2 -- justification`` at the end of the
  offending line, or standalone on the line directly ABOVE it (for
  lines with no room);
- ``# zoolint: disable-file=rule1,rule2 -- justification`` anywhere in
  the file suppresses the rule(s) file-wide;
- ``all`` suppresses every rule.  The `` -- justification`` tail is
  optional but strongly encouraged — the CI gate keeps the tree at zero
  unsuppressed findings, so a suppression is a reviewed decision.

Annotations the rules read (conventions, not syntax extensions):

- ``# guarded-by: <lock>`` on an attribute-initialising line declares
  that ``self.<attr>`` may only be WRITTEN while ``with self.<lock>:``
  is held (:mod:`rules_concurrency`);
- ``# zoolint: hot-path`` on (or directly above) a ``def`` marks the
  function as a device-adjacent hot path where host syncs
  (``.block_until_ready()``, ``np.asarray``, ``float()`` on arrays) are
  findings (:mod:`rules_jax`).

Static analysis is approximate by design: the traced-function set is
built from local evidence (decorators, ``jax.jit(f)`` call sites,
functions passed to ``lax.scan``/``fori_loop``/..., plus transitive
local calls), so a function jitted from another module is not seen.
The rules err toward precision (few false positives) because the CI
gate makes every finding actionable.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Iterator

from analytics_zoo_tpu.analysis.findings import Finding, Severity

__all__ = ["Rule", "LintModule", "ALL_RULES", "lint_source", "lint_file",
           "lint_paths", "iter_python_files"]

_SUPPRESS_RE = re.compile(
    r"zoolint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[\w\-]+(?:\s*,\s*[\w\-]+)*)"
    r"(?:\s*--\s*(?P<why>.*))?")
_HOT_PATH_RE = re.compile(r"zoolint:\s*hot-path")
_GUARDED_BY_RE = re.compile(r"guarded-by:\s*(?P<lock>[\w.]+)")

# Names whose call means "this callable is jit/scan traced".  The VALUE
# is the positions of callable args that become traced (None = arg 0
# only for jit-likes; control-flow primitives trace several).
_JIT_NAMES = {
    "jax.jit", "jit", "jax.pjit", "pjit",
    "jax.experimental.pjit.pjit", "jax.named_call",
}
_TRACING_CALLS = {
    "jax.lax.scan": (0,), "lax.scan": (0,),
    "jax.lax.fori_loop": (2,), "lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1), "lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2), "lax.cond": (1, 2),
    "jax.lax.switch": (1,), "lax.switch": (1,),
    "jax.lax.map": (0,), "lax.map": (0,),
    "jax.lax.associative_scan": (0,), "lax.associative_scan": (0,),
}
_PARTIAL_NAMES = {"functools.partial", "partial"}


class Rule:
    """Base rule: subclasses set ``name``/``severity``/``description``
    and implement :meth:`check` yielding :class:`Finding`s (leave
    ``suppressed`` False — the engine applies suppressions)."""

    name = "abstract"
    severity = Severity.WARNING
    description = ""

    def check(self, mod: "LintModule") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: "LintModule", node: ast.AST, message: str,
                **data) -> Finding:
        return Finding(rule=self.name, severity=self.severity,
                       path=mod.path, line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, data=data)


@dataclass
class LintModule:
    """One parsed file plus everything the rules need to read it."""

    path: str
    source: str
    tree: ast.Module
    lines: list[str]
    #: line -> raw comment text (without the leading ``#``)
    comments: dict[int, str] = field(default_factory=dict)
    #: line -> set of rule names disabled on that line
    suppressions: dict[int, set] = field(default_factory=dict)
    file_suppressions: set = field(default_factory=set)
    #: suppression-comment lines missing a `` -- justification`` tail
    unjustified_suppressions: dict[int, str] = field(default_factory=dict)
    #: lines carrying a ``# zoolint: hot-path`` annotation
    hot_path_lines: set = field(default_factory=set)
    #: line -> lock name from a ``# guarded-by: <lock>`` annotation
    guarded_by_lines: dict[int, str] = field(default_factory=dict)
    #: local name -> canonical dotted path (``np`` -> ``numpy``)
    aliases: dict[str, str] = field(default_factory=dict)
    #: child node -> parent node, for ancestor walks
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)
    #: FunctionDef / AsyncFunctionDef / Lambda nodes that are jit- or
    #: scan-traced (directly or via transitive local calls)
    traced: set = field(default_factory=set)

    # -- name resolution ------------------------------------------------
    def qualname(self, node: ast.AST) -> str | None:
        """Dotted name of a Name/Attribute chain with import aliases
        resolved at the root (``np.random.rand`` -> ``numpy.random.rand``);
        None for anything not a plain chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return a
        return None

    def functions(self) -> Iterator[ast.AST]:
        for n in ast.walk(self.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield n

    def is_hot_path(self, fn: ast.AST) -> bool:
        """Annotated ``# zoolint: hot-path`` on/above the def (above the
        first decorator when decorated)."""
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        first = min([d.lineno for d in fn.decorator_list] + [fn.lineno])
        return any(ln in self.hot_path_lines
                   for ln in range(first - 1, fn.lineno + 1))

    def suppressed_rules_at(self, line: int) -> set:
        return self.file_suppressions | self.suppressions.get(line, set())


def scope_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body WITHOUT descending into nested
    function/lambda scopes — their statements belong to their own
    per-function check, not the enclosing one's."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _collect_comments(mod: LintModule) -> None:
    try:
        tokens = tokenize.generate_tokens(io.StringIO(mod.source).readline)
        comments = [(t.start[0], t.start[1], t.string[1:].strip())
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        return
    for line, col, text in comments:
        mod.comments[line] = text
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group("rules").split(",")}
            if not (m.group("why") or "").strip():
                mod.unjustified_suppressions[line] = m.group("rules")
            if m.group("scope"):
                mod.file_suppressions |= rules
            else:
                mod.suppressions.setdefault(line, set()).update(rules)
                # a standalone suppression comment covers the next line
                # (for statements with no room at the end of the line)
                if mod.lines[line - 1].lstrip().startswith("#"):
                    mod.suppressions.setdefault(line + 1,
                                                set()).update(rules)
        if _HOT_PATH_RE.search(text):
            mod.hot_path_lines.add(line)
        m = _GUARDED_BY_RE.search(text)
        if m:
            mod.guarded_by_lines[line] = m.group("lock")


def _collect_aliases(mod: LintModule) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                mod.aliases[a.asname or a.name] = \
                    f"{node.module}.{a.name}"


def _collect_parents(mod: LintModule) -> None:
    for parent in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(parent):
            mod.parents[child] = parent


def _callable_arg_nodes(mod: LintModule, call: ast.Call,
                        positions: tuple) -> list:
    out = []
    for i in positions:
        if i < len(call.args):
            out.append(call.args[i])
    return out


def _collect_traced(mod: LintModule) -> None:
    """Seed the traced set from jit decorators / jit(f) call sites /
    control-flow-primitive callables, then propagate through local
    calls (``train_step`` calls ``one_step`` => ``one_step`` traced)."""
    defs_by_name: dict[str, list] = {}
    for fn in mod.functions():
        defs_by_name.setdefault(fn.name, []).append(fn)

    def is_jit_expr(node: ast.AST) -> bool:
        q = mod.qualname(node)
        if q in _JIT_NAMES:
            return True
        # partial(jax.jit, ...) / partial(jit, donate_argnums=...)
        if isinstance(node, ast.Call) \
                and mod.qualname(node.func) in _PARTIAL_NAMES \
                and node.args and mod.qualname(node.args[0]) in _JIT_NAMES:
            return True
        return False

    def mark(node: ast.AST):
        if isinstance(node, ast.Lambda):
            mod.traced.add(node)
        elif isinstance(node, ast.Name):
            for fn in defs_by_name.get(node.id, ()):
                mod.traced.add(fn)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_expr(d) or
                   (isinstance(d, ast.Call) and is_jit_expr(d.func))
                   for d in node.decorator_list):
                mod.traced.add(node)
        elif isinstance(node, ast.Call):
            q = mod.qualname(node.func)
            if is_jit_expr(node.func) and node.args:
                mark(node.args[0])
            elif q in _TRACING_CALLS:
                for arg in _callable_arg_nodes(mod, node,
                                               _TRACING_CALLS[q]):
                    mark(arg)

    # transitive closure over local call edges: anything a traced
    # function calls by bare name (and that is defined in this module)
    # runs under the same trace
    changed = True
    while changed:
        changed = False
        for fn in list(mod.traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name):
                    for callee in defs_by_name.get(node.func.id, ()):
                        if callee not in mod.traced:
                            mod.traced.add(callee)
                            changed = True


def parse_module(source: str, path: str = "<string>") -> LintModule:
    tree = ast.parse(source)
    mod = LintModule(path=path, source=source, tree=tree,
                     lines=source.splitlines())
    _collect_comments(mod)
    _collect_aliases(mod)
    _collect_parents(mod)
    _collect_traced(mod)
    return mod


def _apply_suppressions(mod: LintModule,
                        findings: list[Finding]) -> list[Finding]:
    out = []
    for f in findings:
        rules = mod.suppressed_rules_at(f.line)
        if f.rule in rules or "all" in rules:
            f = Finding(rule=f.rule, severity=f.severity, path=f.path,
                        line=f.line, col=f.col, message=f.message,
                        suppressed=True, data=f.data)
        out.append(f)
    return out


def lint_source(source: str, path: str = "<string>",
                rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Run the rule set over one source string; returns ALL findings,
    suppressed ones flagged (callers filter on ``.suppressed``)."""
    try:
        mod = parse_module(source, path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity=Severity.ERROR,
                        path=path, line=e.lineno or 0,
                        message=f"could not parse: {e.msg}")]
    findings: list[Finding] = []
    for rule in (ALL_RULES if rules is None else rules):
        findings.extend(rule.check(mod))
    return _apply_suppressions(mod, findings)


def lint_file(path: str,
              rules: Iterable[Rule] | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path, rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/dirs into a sorted walk of ``.py`` files, skipping
    hidden and cache directories."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirnames, files in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith(".")
                                 and d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths: Iterable[str],
               rules: Iterable[Rule] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules))
    return findings


class BareSuppressionRule(Rule):
    """A suppression is a reviewed decision; the `` -- justification``
    tail is where the review lives.  A bare ``# zoolint: disable=r``
    silences a detector with no recorded reason — flagged so the
    justification trail stays complete (CI keeps the tree at zero
    findings, so every suppression must defend itself)."""

    name = "bare-suppression"
    severity = Severity.WARNING
    description = ("`# zoolint: disable=` without a ` -- justification` "
                   "tail — record why the finding is acceptable")

    def check(self, mod: LintModule) -> Iterator[Finding]:
        for line, rules in sorted(mod.unjustified_suppressions.items()):
            yield Finding(
                rule=self.name, severity=self.severity, path=mod.path,
                line=line,
                message=f"suppression of [{rules}] carries no "
                "justification — append ` -- <why this is safe>` so "
                "the next reader (and re-audit) knows the reasoning",
                data={"rules": rules})


# Assembled at the bottom so the rule modules can import the engine.
from analytics_zoo_tpu.analysis.rules_jax import JAX_RULES  # noqa: E402
from analytics_zoo_tpu.analysis.rules_concurrency import (  # noqa: E402
    CONCURRENCY_RULES,
)

ALL_RULES: tuple = JAX_RULES + CONCURRENCY_RULES \
    + (BareSuppressionRule(),)
