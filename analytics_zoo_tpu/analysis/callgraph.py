"""Whole-program symbol table + call graph (Tier 3 input, "zoosan").

Tier-1 rules see one file at a time, which is exactly the blind spot
for lock discipline: an ABBA deadlock assembled from a broker lock in
``serving/`` and a registry lock in ``metrics/`` has no single-file
witness, and a helper that writes shared state is safe only because its
*callers* (in another module) hold the lock.  This module parses every
file of a package into the Tier-1 :class:`LintModule` shape and links
them:

- **Symbol table** — classes and functions by module, methods by name,
  every ``threading.Lock``/``RLock``/``Condition`` attribute or
  module-level lock with a canonical program-wide id
  (``Broker._cv``, ``analytics_zoo_tpu.common.engine._LOCK``);
- **Call graph** — call sites resolved through import aliases
  (``from x import f``), ``self.method()`` dispatch, module-level
  names, and unique-method-name matching (``x.hset_many()`` resolves
  when exactly one class in the program defines ``hset_many``);
- **Lock facts** — per function: the with-statement lock acquisitions
  (with the locks already held at each), and the calls made while
  holding locks.  :mod:`rules_interproc` closes these transitively
  into the whole-package lock graph and the guarded-by inference.

Resolution is deliberately conservative (a call that cannot be
resolved contributes nothing) — the consumers gate CI, so precision
beats recall.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterator

from analytics_zoo_tpu.analysis.astlint import (
    LintModule,
    iter_python_files,
    parse_module,
)

__all__ = ["Program", "FunctionInfo", "LockAttr", "LockAcquisition",
           "CallSite", "load_program"]

#: constructors whose result is a mutual-exclusion primitive the
#: analyses track (Semaphore deliberately excluded: it is a counter,
#: not a critical-section guard, so "held" has no exclusion meaning)
LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
}


@dataclass(frozen=True)
class LockAttr:
    """One lock-typed attribute: ``self.<attr>`` of ``cls`` (or a
    module-level name when ``cls`` is None)."""

    module: str
    cls: str | None
    attr: str
    factory: str  #: e.g. ``threading.Condition``
    line: int

    @property
    def lock_id(self) -> str:
        # always module-qualified: two same-named classes in different
        # modules own DIFFERENT locks, and merging them would fabricate
        # cross-module cycles that no execution can deadlock on
        if self.cls is not None:
            return f"{self.module}.{self.cls}.{self.attr}"
        return f"{self.module}.{self.attr}"


@dataclass
class LockAcquisition:
    """One ``with <lock>:`` entry inside a function."""

    lock_id: str
    node: ast.With | ast.AsyncWith
    held: tuple  #: lock ids already held (innermost last)


@dataclass
class CallSite:
    """One call inside a function, with resolution candidates."""

    node: ast.Call
    held: tuple  #: lock ids held at the call
    callees: tuple  #: resolved (module, qualname) keys, possibly empty


@dataclass
class FunctionInfo:
    """One function/method with the lock facts the interprocedural
    rules consume."""

    module: str
    qualname: str  #: ``Class.method`` or bare function name
    node: ast.AST
    mod: LintModule
    cls: str | None = None
    acquisitions: list = field(default_factory=list)
    calls: list = field(default_factory=list)

    @property
    def key(self) -> tuple:
        return (self.module, self.qualname)

    @property
    def location(self) -> str:
        return f"{self.mod.path}:{self.node.lineno}"


@dataclass
class Program:
    """The linked whole-package view."""

    root: str
    package: str
    #: dotted module name -> LintModule
    modules: dict = field(default_factory=dict)
    #: (module, qualname) -> FunctionInfo
    functions: dict = field(default_factory=dict)
    #: class name -> [(module, ast.ClassDef)]
    classes: dict = field(default_factory=dict)
    #: method name -> [FunctionInfo] (across all classes)
    methods_by_name: dict = field(default_factory=dict)
    #: (module, cls or None, attr) -> LockAttr
    lock_attrs: dict = field(default_factory=dict)
    #: (module, class name) -> {attr -> LockAttr} for that class's locks
    class_locks: dict = field(default_factory=dict)
    #: module dotted name -> {name -> LockAttr} for module-level locks
    module_locks: dict = field(default_factory=dict)

    # -- lookups --------------------------------------------------------
    def module_of_path(self, path: str) -> LintModule | None:
        for mod in self.modules.values():
            if mod.path == path:
                return mod
        return None

    def function(self, module: str, qualname: str) -> FunctionInfo | None:
        return self.functions.get((module, qualname))

    def iter_functions(self) -> Iterator[FunctionInfo]:
        return iter(self.functions.values())


def _module_name(root: str, package: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    parts = rel[:-3].split(os.sep)  # strip ".py"
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join([package] + parts) if parts else package


def _enclosing_class(mod: LintModule, fn: ast.AST) -> str | None:
    for anc in mod.ancestors(fn):
        if isinstance(anc, ast.ClassDef):
            return anc.name
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None  # nested function: not a method
    return None


def _collect_locks(prog: Program, name: str, mod: LintModule) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        factory = mod.qualname(value.func)
        if factory not in LOCK_FACTORIES:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                fn = mod.enclosing_function(node)
                cls = _enclosing_class(mod, fn) if fn else None
                if cls is not None:
                    la = LockAttr(module=name, cls=cls, attr=t.attr,
                                  factory=factory, line=node.lineno)
                    prog.class_locks.setdefault(
                        (name, cls), {})[t.attr] = la
                    prog.lock_attrs[(name, cls, t.attr)] = la
            elif isinstance(t, ast.Name):
                if mod.enclosing_function(node) is not None:
                    continue  # function-local lock: not shared state
                cls = _enclosing_class(mod, node)
                if cls is not None:  # class-body attribute lock
                    la = LockAttr(module=name, cls=cls, attr=t.id,
                                  factory=factory, line=node.lineno)
                    prog.class_locks.setdefault(
                        (name, cls), {})[t.id] = la
                    prog.lock_attrs[(name, cls, t.id)] = la
                    continue
                la = LockAttr(module=name, cls=None, attr=t.id,
                              factory=factory, line=node.lineno)
                prog.module_locks.setdefault(name, {})[t.id] = la
                prog.lock_attrs[(name, None, t.id)] = la


def _lock_id_of_expr(prog: Program, mod: LintModule, name: str,
                     cls: str | None, expr: ast.AST) -> str | None:
    """Canonical program-wide lock id for a with-statement context
    expression, or None when it is not a known lock.

    Resolution order: ``self.<attr>`` against the enclosing class's
    typed locks (module-and-class-scoped ids, plus a lock-ish-name
    fallback scoped the same way), a dotted/bare name against
    module-level locks (through import aliases), then
    ``<anything>.<attr>`` against a program-unique lock attribute
    name.  Anything unresolvable yields None: a merely lock-NAMED
    local variable must not become a program-wide node, or two
    unrelated locals called ``lock`` in different modules would
    fabricate a cycle no execution can deadlock on.
    """
    q = mod.qualname(expr)
    if q is None:
        return None
    if q.startswith("self."):
        attr = q[5:]
        if cls is not None \
                and attr in prog.class_locks.get((name, cls), {}):
            return f"{name}.{cls}.{attr}"
        # untyped attr (e.g. a lock handed in via the constructor):
        # the name heuristic stays module+class-scoped
        if cls is not None \
                and ("lock" in attr.lower() or attr.endswith("_cv")):
            return f"{name}.{cls}.{attr}"
        return None
    # module-level: q is alias-resolved, e.g. pkg.common.engine._LOCK
    head, _, leaf = q.rpartition(".")
    if head in prog.module_locks and leaf in prog.module_locks[head]:
        return f"{head}.{leaf}"
    if not head and leaf in prog.module_locks.get(name, {}):
        return f"{name}.{leaf}"
    # `from sibling import LOCK` outside the package root resolves to a
    # bare module name — match it against loaded modules by suffix
    if head:
        for mod_name, locks in prog.module_locks.items():
            if leaf in locks and (mod_name == head
                                  or mod_name.endswith("." + head)):
                return f"{mod_name}.{leaf}"
    # unique lock-attribute name anywhere in the program
    owners = {(m, c) for (m, c, a) in prog.lock_attrs
              if a == leaf and c is not None}
    if len(owners) == 1:
        ((m, c),) = owners
        return f"{m}.{c}.{leaf}"
    return None


def _resolve_call(prog: Program, mod: LintModule, name: str,
                  cls: str | None, call: ast.Call) -> tuple:
    """Candidate (module, qualname) keys for a call node."""
    func = call.func
    out: list[tuple] = []
    if isinstance(func, ast.Name):
        target = mod.aliases.get(func.id, func.id)
        if "." in target:  # from x import f
            m, _, f = target.rpartition(".")
            if (m, f) in prog.functions:
                out.append((m, f))
        if (name, func.id) in prog.functions:
            out.append((name, func.id))
    elif isinstance(func, ast.Attribute):
        recv, attr = func.value, func.attr
        if isinstance(recv, ast.Name) and recv.id == "self" \
                and cls is not None:
            if (name, f"{cls}.{attr}") in prog.functions:
                out.append((name, f"{cls}.{attr}"))
                return tuple(out)
        q = mod.qualname(func)
        if q is not None and "." in q:
            m, _, f = q.rpartition(".")
            if (m, f) in prog.functions:
                out.append((m, f))
        if not out:
            # unique method name across the program's classes
            owners = prog.methods_by_name.get(attr, ())
            if len(owners) == 1:
                out.append(owners[0].key)
    return tuple(out)


def _collect_function_facts(prog: Program, name: str,
                            mod: LintModule) -> None:
    for fn in mod.functions():
        cls = _enclosing_class(mod, fn)
        qual = f"{cls}.{fn.name}" if cls else fn.name
        info = FunctionInfo(module=name, qualname=qual, node=fn,
                            mod=mod, cls=cls)
        key = info.key
        if key in prog.functions:
            continue  # first definition wins (overloads are rare)
        prog.functions[key] = info
        if cls is not None:
            prog.methods_by_name.setdefault(fn.name, []).append(info)

    # second pass: walk bodies with a held-lock stack, recording
    # acquisitions and call sites (own-scope only — a nested def gets
    # its own FunctionInfo and its own walk)
    for info in [f for f in prog.functions.values() if f.module == name]:
        def walk(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not info.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_id_of_expr(prog, info.mod, name,
                                           info.cls, item.context_expr)
                    if lid is not None:
                        info.acquisitions.append(LockAcquisition(
                            lock_id=lid, node=node, held=held))
                        held = held + (lid,)
                for child in node.body:
                    walk(child, held)
                return
            if isinstance(node, ast.Call):
                callees = _resolve_call(prog, info.mod, name, info.cls,
                                        node)
                info.calls.append(CallSite(node=node, held=held,
                                           callees=callees))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in ast.iter_child_nodes(info.node):
            walk(stmt, ())


def load_program(root: str, package: str | None = None) -> Program:
    """Parse every ``.py`` under ``root`` into one linked
    :class:`Program`.  ``package`` defaults to the directory's name
    (``analytics_zoo_tpu`` for the repo's own tree)."""
    root = os.path.abspath(root)
    package = package or os.path.basename(root.rstrip(os.sep))
    prog = Program(root=root, package=package)

    for path in iter_python_files([root]):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = parse_module(source, path)
        except (OSError, SyntaxError):
            continue  # unparseable files are Tier-1 findings already
        prog.modules[_module_name(root, package, path)] = mod

    # symbol passes: locks first (call/lock resolution reads them),
    # then the function facts
    for name, mod in prog.modules.items():
        _collect_locks(prog, name, mod)
    for name, mod in prog.modules.items():
        _collect_function_facts(prog, name, mod)
    return prog
