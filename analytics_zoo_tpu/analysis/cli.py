"""zoolint CLI — run the Tier-1 AST rules over files/trees.

``python tools/zoolint.py analytics_zoo_tpu/`` is the pre-commit / CI
entry: exit 0 on a clean tree, 1 when any unsuppressed finding exists
(2 on usage errors), so it composes with ``&&`` chains and CI steps.
``--format json`` emits the machine shape (``findings`` + ``summary``);
``--show-suppressed`` includes suppressed findings in text output for
auditing the justification trail.  ``--changed`` lints only the files
modified vs ``git merge-base HEAD origin/main`` (fallback: the
working-tree diff) — the fast pre-commit loop ``tools/precommit.sh``
wires up.  ``--whole-program`` adds the Tier-3 interprocedural pass
(cross-module lock-order cycles + guarded-by inference) over every
directory target.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from analytics_zoo_tpu.analysis.astlint import ALL_RULES, lint_paths
from analytics_zoo_tpu.analysis.findings import render_json, render_text


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX / concurrency AST linter (Tiers 1+3 of "
                    "analytics_zoo_tpu.analysis)")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files or directories to lint "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated subset of rules to run")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--changed", action="store_true",
                   help="lint only .py files changed vs `git merge-base "
                        "HEAD origin/main` (fallback: working-tree "
                        "diff), ignoring positional paths")
    p.add_argument("--whole-program", action="store_true",
                   help="also run the interprocedural pass (cross-"
                        "module lock-order + guarded-by inference) "
                        "over each directory target")
    return p


def _git(*args: str, cwd: str | None = None) -> subprocess.CompletedProcess:
    return subprocess.run(["git", *args], capture_output=True,
                          text=True, cwd=cwd)


def changed_paths() -> list | None:
    """``.py`` files changed vs the merge base with origin/main, plus
    working-tree modifications and untracked files.  None when not in
    a git checkout (callers turn that into a usage error — silently
    linting nothing must not read as clean)."""
    top = _git("rev-parse", "--show-toplevel")
    if top.returncode != 0:
        return None
    root = top.stdout.strip()
    files: set = set()
    # every git call runs FROM the repo root: both the `*.py` pathspec
    # and the printed paths are cwd-relative, so invoking from a
    # subdirectory would otherwise read as "nothing changed" (exit 0)
    # while lintable changes exist above the cwd
    base = _git("merge-base", "HEAD", "origin/main", cwd=root)
    if base.returncode == 0:
        diff = _git("diff", "--name-only", base.stdout.strip(),
                    "--", "*.py", cwd=root)
        files |= set(diff.stdout.split())
    # fallback AND supplement: uncommitted + untracked work is exactly
    # what a pre-commit hook needs to see
    for args in (("diff", "--name-only", "HEAD", "--", "*.py"),
                 ("ls-files", "--others", "--exclude-standard",
                  "--", "*.py")):
        out = _git(*args, cwd=root)
        if out.returncode == 0:
            files |= set(out.stdout.split())
    # fixture corpora are DELIBERATELY dirty (planted positives) — their
    # own tests lint them with the right expectations
    files = {f for f in files
             if not f.startswith("tests/resources/")}
    resolved = [os.path.join(root, f) for f in sorted(files)]
    return [p for p in resolved if os.path.exists(p)]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name:<18} {rule.severity:<7} "
                  f"{rule.description}")
        return 0

    rules = ALL_RULES
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")}
        unknown = wanted - {r.name for r in ALL_RULES}
        if unknown:
            print(f"zoolint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in ALL_RULES if r.name in wanted]

    if args.changed:
        paths = changed_paths()
        if paths is None:
            print("zoolint: --changed needs a git checkout",
                  file=sys.stderr)
            return 2
        if not paths:
            print("zoolint: no changed .py files — nothing to lint")
            return 0
    else:
        paths = args.paths
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            # a typo'd path must NOT read as "0 findings, clean": a CI
            # step pointed at nothing would stay green forever
            print(f"zoolint: no such path(s): {missing}",
                  file=sys.stderr)
            return 2

    findings = lint_paths(paths, rules)

    if args.whole_program:
        from analytics_zoo_tpu.analysis.rules_interproc import (
            lint_program,
        )

        roots = [p for p in paths if os.path.isdir(p)]
        if args.changed and not roots:
            # changed paths are always files — the fast loop still
            # gets the cross-module pass, over the installed package
            pkg = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            if os.path.isdir(pkg):
                roots = [pkg]
        for p in roots:
            findings.extend(lint_program(p))

    if args.format == "json":
        print(render_json(findings))
    else:
        print(render_text(findings, show_suppressed=args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
