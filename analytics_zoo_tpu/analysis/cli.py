"""zoolint CLI — run the Tier-1 AST rules over files/trees.

``python tools/zoolint.py analytics_zoo_tpu/`` is the pre-commit / CI
entry: exit 0 on a clean tree, 1 when any unsuppressed finding exists
(2 on usage errors), so it composes with ``&&`` chains and CI steps.
``--format json`` emits the machine shape (``findings`` + ``summary``);
``--show-suppressed`` includes suppressed findings in text output for
auditing the justification trail.
"""

from __future__ import annotations

import argparse
import os
import sys

from analytics_zoo_tpu.analysis.astlint import ALL_RULES, lint_paths
from analytics_zoo_tpu.analysis.findings import render_json, render_text


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX / concurrency AST linter (Tier 1 of "
                    "analytics_zoo_tpu.analysis)")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files or directories to lint "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated subset of rules to run")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name:<18} {rule.severity:<7} "
                  f"{rule.description}")
        return 0

    rules = ALL_RULES
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")}
        unknown = wanted - {r.name for r in ALL_RULES}
        if unknown:
            print(f"zoolint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in ALL_RULES if r.name in wanted]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must NOT read as "0 findings, clean": a CI step
        # pointed at nothing would stay green forever
        print(f"zoolint: no such path(s): {missing}", file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, rules)
    if args.format == "json":
        print(render_json(findings))
    else:
        print(render_text(findings, show_suppressed=args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
