"""JAX pitfall rules (Tier 1).

Four detectors for the hazards a jitted codebase cannot surface at
runtime: the symptom of each is silent wrongness or silent slowness,
never an exception.

- ``jit-side-effect``: a Python side effect (``print``, ``time.time``,
  ``np.random``, ...) inside a jit-decorated or scan-traced function
  runs ONCE at trace time and never again — timing reads trace time,
  prints vanish, np.random freezes one sample into the graph.
- ``prng-reuse``: the same PRNG key consumed by two sampling calls
  without an intervening ``split``/``fold_in`` yields identical (not
  independent) draws.
- ``host-sync``: ``.block_until_ready()`` / ``np.asarray`` /
  ``float()``/``int()`` on arrays inside a ``# zoolint: hot-path``
  annotated function stalls the dispatch pipeline — the async-dispatch
  win the fit loop / serving cycle / prefetch plane exists to get.
- ``nondonated-carry``: a jit over a training-carry signature
  (``opt_state``/``carry``) without ``donate_argnums`` doubles peak
  memory — the old buffers stay live across the update.
- ``raw-jit``: a ``jax.jit``/``pjit`` call site outside the compile
  plane (``compile_step`` / ``timed_compile``) produces programs the
  persistent cache, AOT warmup, ``zoo_compile_seconds`` metering and
  the HLO graph lint never see.
- ``raw-remat``: a ``jax.checkpoint``/``jax.remat`` call site outside
  ``apply_remat`` hard-codes a remat decision the sharding plan's
  ``remat_rules`` and the oracle's sharding × remat sweep can never
  override.
"""

from __future__ import annotations

import ast
from typing import Iterator

from analytics_zoo_tpu.analysis.astlint import (
    LintModule,
    Rule,
    _JIT_NAMES,
    _PARTIAL_NAMES,
    scope_walk,
)
from analytics_zoo_tpu.analysis.findings import Finding, Severity

__all__ = ["JAX_RULES", "JitSideEffectRule", "PrngReuseRule",
           "HostSyncRule", "NonDonatedCarryRule", "RawJitRule",
           "RawRematRule", "RawPallasCallRule"]

# Calls that are host side effects when traced.  Exact qualnames plus
# the numpy.random.* / random.* families.
_SIDE_EFFECT_EXACT = {
    "print": "output goes to the TRACE, not the run — use jax.debug.print",
    "time.time": "reads TRACE time once, then is a baked-in constant",
    "time.time_ns": "reads TRACE time once, then is a baked-in constant",
    "time.perf_counter":
        "reads TRACE time once, then is a baked-in constant",
    "time.monotonic": "reads TRACE time once, then is a baked-in constant",
    "time.sleep": "sleeps at trace time only; no-op in the compiled step",
    "input": "blocks tracing; never runs in the compiled step",
    "breakpoint": "fires at trace time only — use jax.debug.breakpoint",
}
_SIDE_EFFECT_PREFIXES = {
    "numpy.random.":
        "samples ONCE at trace time — the same values replay every "
        "step; use jax.random with a per-step key",
    "random.": "samples ONCE at trace time — the same values replay "
               "every step; use jax.random with a per-step key",
}

# jax.random attrs that DERIVE keys rather than consume them for
# sampling — exempt both as calls and as reuse producers.
_KEY_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "wrap_key_data",
                 "key_data", "clone", "key_impl"}

_CARRY_PARAMS = {"opt_state", "carry"}

# Methods that mutate their receiver in place (list/set/dict/deque API
# union) — used by the guarded-by rule too.
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popitem", "popleft", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
}


class JitSideEffectRule(Rule):
    name = "jit-side-effect"
    severity = Severity.ERROR
    description = ("Python side effect (print / time.* / np.random / "
                   "random) inside a jit- or scan-traced function")

    def check(self, mod: LintModule) -> Iterator[Finding]:
        seen: set = set()
        # descending lineno order: an inner traced def is walked before
        # its enclosing traced def, so a call is attributed to the
        # INNERMOST function deterministically (mod.traced is a set —
        # raw iteration order would flip the attribution run-to-run)
        for fn in sorted(mod.traced,
                         key=lambda f: getattr(f, "lineno", 0),
                         reverse=True):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                q = mod.qualname(node.func)
                if q is None:
                    continue
                why = _SIDE_EFFECT_EXACT.get(q)
                if why is None:
                    for prefix, reason in _SIDE_EFFECT_PREFIXES.items():
                        if q.startswith(prefix):
                            why = reason
                            break
                if why is None:
                    continue
                fname = getattr(fn, "name", "<lambda>")
                yield self.finding(
                    mod, node,
                    f"`{q}` inside traced function `{fname}`: {why}",
                    call=q, function=fname)


class PrngReuseRule(Rule):
    name = "prng-reuse"
    severity = Severity.WARNING
    description = ("PRNG key passed to two sampling calls without "
                   "split/fold_in between them")

    def _events(self, mod: LintModule, fn) -> list:
        """(line, col, kind, var) events in source order: 'use' = key
        var consumed by a jax.random sampler, 'def' = var reassigned.
        Scope-limited: nested defs/lambdas hold their OWN key scopes
        (they are checked separately), so their events must not bleed
        into the enclosing function's reuse tracking."""
        events = []
        for node in scope_walk(fn):
            if isinstance(node, ast.Call):
                q = mod.qualname(node.func)
                if q and q.startswith("jax.random.") \
                        and q.rsplit(".", 1)[1] not in _KEY_DERIVERS \
                        and node.args \
                        and isinstance(node.args[0], ast.Name):
                    events.append((node.lineno, node.col_offset, "use",
                                   node.args[0].id, node))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            events.append((leaf.lineno, leaf.col_offset,
                                           "def", leaf.id, node))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name):
                events.append((node.target.lineno, node.target.col_offset,
                               "def", node.target.id, node))
        return sorted(events, key=lambda e: (e[0], e[1]))

    def check(self, mod: LintModule) -> Iterator[Finding]:
        for fn in mod.functions():
            used: dict[str, int] = {}
            for line, _col, kind, var, node in self._events(mod, fn):
                if kind == "def":
                    used.pop(var, None)
                elif var in used:
                    yield self.finding(
                        mod, node,
                        f"PRNG key `{var}` reused (first consumed at "
                        f"line {used[var]}) without split/fold_in — "
                        "identical draws, not independent ones",
                        key=var, first_use_line=used[var])
                else:
                    used[var] = line


class HostSyncRule(Rule):
    name = "host-sync"
    severity = Severity.WARNING
    description = ("device sync (block_until_ready / device_get / "
                   "np.asarray / .item() / float()/int() on arrays) "
                   "inside a `# zoolint: hot-path` function; syncs "
                   "lexically inside the dispatch loop itself are "
                   "called out as blocking the next feed")

    _SYNC_QUALNAMES = {
        "jax.block_until_ready", "jax.device_get",
        "numpy.asarray", "numpy.array",
    }

    def _in_hot_path(self, mod: LintModule, node: ast.AST) -> bool:
        fn = mod.enclosing_function(node)
        while fn is not None:
            if mod.is_hot_path(fn):
                return True
            fn = mod.enclosing_function(fn)
        return False

    def _in_loop(self, mod: LintModule, node: ast.AST) -> bool:
        """Is ``node`` lexically inside a for/while loop of its
        enclosing function (not counting outer functions' loops)?  A
        sync there runs BETWEEN dispatches: it blocks the host until
        the device drains before the next batch can even be fed."""
        fn = mod.enclosing_function(node)
        cur = mod.parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            cur = mod.parents.get(cur)
        return False

    def check(self, mod: LintModule) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            what = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                what = ".block_until_ready()"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" \
                    and not node.args and not node.keywords:
                what = ".item()"
            else:
                q = mod.qualname(node.func)
                if q in self._SYNC_QUALNAMES:
                    what = q
                elif q in ("float", "int") and len(node.args) == 1 \
                        and not isinstance(node.args[0], ast.Constant):
                    what = f"{q}()"
            if what is None or not self._in_hot_path(mod, node):
                continue
            if self._in_loop(mod, node):
                yield self.finding(
                    mod, node,
                    f"{what} between dispatch and the next feed in a "
                    "hot-path loop — the host blocks until the device "
                    "drains before it can even feed the next batch, "
                    "serializing every iteration; hoist it out of the "
                    "loop (or onto a background thread) or suppress "
                    "with a justification if the per-iteration sync is "
                    "deliberate",
                    call=what, in_loop=True)
            else:
                yield self.finding(
                    mod, node,
                    f"{what} on a hot path forces a host/device sync — "
                    "it stalls async dispatch until the device catches "
                    "up; move it off the hot path or suppress with a "
                    "justification if the sync (or host-only data) is "
                    "intentional",
                    call=what)


class NonDonatedCarryRule(Rule):
    name = "nondonated-carry"
    severity = Severity.WARNING
    description = ("jit over a training-carry signature without "
                   "donate_argnums — old buffers stay live, doubling "
                   "peak memory")

    @staticmethod
    def _donates(call: ast.Call) -> bool:
        return any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in call.keywords)

    def _carry_params(self, fn) -> list[str]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return []
        names = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
        return [n for n in names if n in _CARRY_PARAMS]

    def check(self, mod: LintModule) -> Iterator[Finding]:
        defs_by_name: dict[str, list] = {}
        for fn in mod.functions():
            defs_by_name.setdefault(fn.name, []).append(fn)

        def jit_no_donate(expr) -> bool:
            """expr is a bare-jit reference or a jit call with no
            donation kwargs."""
            if mod.qualname(expr) in _JIT_NAMES:
                return True
            if isinstance(expr, ast.Call):
                if mod.qualname(expr.func) in _JIT_NAMES:
                    return not self._donates(expr)
                if mod.qualname(expr.func) in _PARTIAL_NAMES \
                        and expr.args \
                        and mod.qualname(expr.args[0]) in _JIT_NAMES:
                    return not self._donates(expr)
            return False

        for fn in mod.functions():
            carries = self._carry_params(fn)
            if not carries:
                continue
            for dec in fn.decorator_list:
                if jit_no_donate(dec):
                    yield self.finding(
                        mod, fn,
                        f"`{fn.name}` carries {carries} but its jit "
                        "does not donate them — pass donate_argnums "
                        "so the update reuses the old buffers",
                        function=fn.name, carries=carries)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and mod.qualname(node.func) in _JIT_NAMES \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and not self._donates(node):
                for fn in defs_by_name.get(node.args[0].id, ()):
                    carries = self._carry_params(fn)
                    if carries:
                        yield self.finding(
                            mod, node,
                            f"jit of `{fn.name}` (carries {carries}) "
                            "without donate_argnums — old buffers stay "
                            "live, doubling peak memory",
                            function=fn.name, carries=carries)


class RawJitRule(Rule):
    """Package code must compile through the compile plane: a raw
    ``jax.jit``/``pjit`` call bypasses the persistent compile cache,
    AOT warmup, ``zoo_compile_seconds`` metering and the HLO graph
    lint/feature extraction — all of which live behind ONE choke point
    (``parallel.plan.compile_step`` → ``compile_cache.timed_compile``).
    A jit whose lowering flows INTO ``timed_compile(...)`` in the same
    expression (the ``timed_compile(jax.jit(f).lower(...))`` idiom) is
    exempt — that IS the choke point."""

    name = "raw-jit"
    severity = Severity.WARNING
    description = ("jax.jit/pjit outside compile_step/timed_compile — "
                   "the program bypasses the compile plane (persistent "
                   "cache, metering, HLO lint)")

    _CHOKE_TAILS = ("timed_compile", "compile_step")
    # subclass knobs (RawRematRule): the offending names, the blessed
    # route to suggest, and what a bypass loses
    _NAMES = _JIT_NAMES
    _ROUTE = "compile_step (parallel/plan.py) / timed_compile"
    _BYPASSES = "the compile plane"

    def _inside_choke(self, mod: LintModule, node: ast.AST) -> bool:
        for a in mod.ancestors(node):
            if isinstance(a, ast.Call):
                q = mod.qualname(a.func)
                if q and q.rsplit(".", 1)[-1] in self._CHOKE_TAILS:
                    return True
        return False

    def _jit_call(self, mod: LintModule, node: ast.AST):
        """The offending jit expression, or None: a ``jax.jit(...)``
        call, or ``partial(jax.jit, ...)``."""
        if not isinstance(node, ast.Call):
            return None
        q = mod.qualname(node.func)
        if q in self._NAMES:
            return q
        if q in _PARTIAL_NAMES and node.args \
                and mod.qualname(node.args[0]) in self._NAMES:
            return mod.qualname(node.args[0])
        return None

    def check(self, mod: LintModule) -> Iterator[Finding]:
        decorator_calls = set()
        for fn in mod.functions():
            for dec in fn.decorator_list:
                q = mod.qualname(dec)
                bare = q in self._NAMES
                call = self._jit_call(mod, dec)
                if bare or call:
                    decorator_calls.add(id(dec))
                    # anchored at the DECORATOR (the offense — and where
                    # a suppression comment naturally sits)
                    yield self.finding(
                        mod, dec,
                        f"`{fn.name}` is wrapped with a raw "
                        f"`{call or q}` decorator — route it through "
                        f"{self._ROUTE} so it shares "
                        f"{self._BYPASSES}, or suppress with a "
                        "justification",
                        function=fn.name)
        for node in ast.walk(mod.tree):
            if id(node) in decorator_calls:
                continue
            call = self._jit_call(mod, node)
            if call is None or self._inside_choke(mod, node):
                continue
            yield self.finding(
                mod, node,
                f"raw `{call}` call bypasses {self._BYPASSES} — use "
                f"{self._ROUTE}, or "
                "suppress with a justification",
                call=call)


# Rematerialization entry points.  `jax.checkpoint` and `jax.remat` are
# aliases; bare names cover `from jax import checkpoint` imports.
_REMAT_NAMES = {
    "jax.checkpoint", "jax.remat", "checkpoint", "remat",
    "jax.ad_checkpoint.checkpoint",
}


class RawRematRule(RawJitRule):
    """Package code must apply rematerialization through the plan: a raw
    ``jax.checkpoint``/``jax.remat`` call hard-codes one remat decision
    at the call site, invisible to the sharding plan's ``remat_rules``
    (``parallel.plan.resolve_remat``) and to the oracle's
    sharding × remat sweep — the per-layer policy the memory plan owns
    becomes unoverridable.  ``apply_remat`` (parallel/plan.py) is the
    ONE blessed ``jax.checkpoint`` site every rule resolves to; a
    checkpoint flowing into ``apply_remat(...)`` is exempt."""

    name = "raw-remat"
    severity = Severity.WARNING
    description = ("jax.checkpoint/jax.remat outside apply_remat — the "
                   "remat decision bypasses the plan's remat_rules "
                   "(resolve_remat) and the oracle's remat sweep")

    _CHOKE_TAILS = ("apply_remat",)
    _NAMES = _REMAT_NAMES
    _ROUTE = ("apply_remat / a plan's remat_rules "
              "(parallel/plan.py)")
    _BYPASSES = "the plan's remat policy"


# Pallas entry points.  Bare `pallas_call` covers
# `from jax.experimental.pallas import pallas_call` imports.
_PALLAS_NAMES = {
    "pl.pallas_call", "pallas.pallas_call", "pallas_call",
    "jax.experimental.pallas.pallas_call",
}


class RawPallasCallRule(RawJitRule):
    """Hand-written kernels live in ``ops/pallas/`` — the kernel plane:
    every kernel there ships a jnp fallback oracle, routes selection
    through a plan's ``kernel_rules`` (``resolve_kernel``), and lowers
    under a ``kernel_*`` label via the compile choke point.  A
    ``pl.pallas_call`` anywhere else hard-codes a kernel decision at
    the call site — no fallback contract, invisible to the fifth rule
    table and to the oracle's kernel-vs-XLA verdicts.  Files in
    ``ops/pallas/`` carry a ``disable-file`` pragma with this
    justification."""

    name = "raw-pallas-call"
    severity = Severity.WARNING
    description = ("pl.pallas_call outside ops/pallas/ — the kernel "
                   "bypasses the kernel plane (fallback oracle, "
                   "kernel_rules selection, kernel_* compile labels)")

    _CHOKE_TAILS = ()
    _NAMES = _PALLAS_NAMES
    _ROUTE = ("a kernel module under ops/pallas/ (fallback oracle + "
              "kernel_rules selection)")
    _BYPASSES = "the kernel plane"


JAX_RULES = (JitSideEffectRule(), PrngReuseRule(), HostSyncRule(),
             NonDonatedCarryRule(), RawJitRule(), RawRematRule(),
             RawPallasCallRule())
