"""Analytic + fitted cost model for the predictive compile plane.

The compile choke point already extracts a static feature vector per
compiled program (``analysis/hlo.py``: matmul FLOPs, bytes touched,
collective bytes, fused-dispatch count) — TpuGraphs (arXiv:2308.13490)
shows exactly these features rank configs well, and tf.data
(arXiv:2101.12127) shows an analytic prior refined online beats blind
search.  This module is both halves:

- :func:`predict_step_seconds` — a **roofline** over the feature
  vector: per-step time = max(flops/peak_flops, bytes/peak_bw) +
  collective_bytes/link_bw + dispatch_overhead/K.  The K term is the
  fused-dispatch amortization the autotuner otherwise discovers by
  measurement (~53 dispatches, BENCH_AUTOTUNE_r08); the ceilings come
  from a small per-platform :class:`PeakTable` with a CPU-calibrated
  default, any field overridable via ``ZOO_ORACLE_PEAKS`` (a JSON
  object, e.g. ``{"dispatch_overhead_s": 4e-4}``).
- :func:`predict_chip_bytes` / :func:`plan_collective_bytes` — per-chip
  memory and per-step interconnect traffic per sharding plan
  (dp/zero1/fsdp/tp memory factors; ring-collective byte counts), the
  inputs of ``plan="auto"``.
- :class:`ResidualModel` — a least-squares fit IN LOG SPACE of
  measured/predicted against the log-features (stdlib only — the
  normal equations are solved by Gaussian elimination, no
  sklearn/numpy.linalg).  Trained from accumulated
  ``ZOO_HLO_REPORT_DIR`` reports (:func:`load_report_rows`, schema v1
  accepted with nulls) joined with BENCH_*.json rows
  (:func:`load_bench_rows`) and the autotuner's persisted decision
  history (:func:`load_tune_log_rows`, ``ZOO_TUNE_LOG_DIR``).  Below
  :data:`MIN_FIT_SAMPLES` joined samples the model reports
  ``ready == False`` and callers fall back to the analytic prediction
  alone — the zero-data path is first-class, not an error.

Consumed by :mod:`analytics_zoo_tpu.analysis.oracle` (the
``ConfigOracle`` that primes the autotuner and resolves
``plan="auto"``); documented in docs/performance.md ("Predictive
compile plane").
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Iterable, Mapping, Sequence

__all__ = [
    "PeakTable", "resolve_peaks", "PLATFORM_PEAKS", "MIN_FIT_SAMPLES",
    "normalize_features", "predict_step_seconds", "predict_steps_per_sec",
    "plan_exposed_fraction", "EXPOSED_FRACTIONS",
    "predict_chip_bytes", "plan_collective_bytes", "PLAN_MEMORY_FACTORS",
    "REMAT_ACTIVATION_FACTORS", "REMAT_FLOPS_FACTORS",
    "DTYPE_PEAK_FACTORS", "plan_dtype", "dtype_peaks",
    "histogram_compute_dtype",
    "KERNEL_BYTE_MODELS", "kernel_bytes", "choose_kernel",
    "ResidualModel", "load_report_rows", "load_bench_rows",
    "load_tune_log_rows", "training_rows",
    "predict_serving_seconds", "serving_bucket_label",
    "load_serving_rows", "SERVING_LABEL_PREFIX",
]

#: below this many joined (features, K, measured steps/sec) samples the
#: residual model refuses to fit and the analytic roofline stands alone
MIN_FIT_SAMPLES = 8


@dataclasses.dataclass(frozen=True)
class PeakTable:
    """Hardware ceilings the roofline divides by.

    ``flops``/``hbm_bytes_per_s``/``link_bytes_per_s`` are per-chip
    peaks; ``dispatch_overhead_s`` is the fixed host cost of one jitted
    dispatch (the quantity ``steps_per_dispatch`` K amortizes);
    ``hbm_bytes`` is the per-chip memory budget ``plan="auto"`` fits
    against.  ``source`` names the table entry (or "env" after a
    ``ZOO_ORACLE_PEAKS`` override) so artifacts record which
    calibration produced a prediction.
    """

    flops: float
    hbm_bytes_per_s: float
    link_bytes_per_s: float
    dispatch_overhead_s: float
    hbm_bytes: float
    source: str = "cpu-default"

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)


#: Per-platform ceilings.  The CPU row is CALIBRATED, not theoretical:
#: dispatch_overhead_s comes from BENCH_AUTOTUNE_r08's measured
#: per-step cost curve (cost(K) = compute + overhead/K over
#: K∈{1..16} gives overhead ≈ 5e-4 s on this harness's host), and the
#: flops/bandwidth rows are order-of-magnitude host numbers — for the
#: dispatch-bound programs the CPU backend exists to exercise, the
#: overhead term dominates and ranking is insensitive to them.  TPU
#: rows use published per-chip peaks (see also TPU_PEAK_FLOPS in
#: bench.py for MFU accounting).
PLATFORM_PEAKS: dict[str, PeakTable] = {
    "cpu": PeakTable(
        flops=5.0e10, hbm_bytes_per_s=2.0e10, link_bytes_per_s=1.0e10,
        dispatch_overhead_s=5.0e-4, hbm_bytes=float(4 << 30),
        source="cpu-default"),
    "tpu-v4": PeakTable(
        flops=2.75e14, hbm_bytes_per_s=1.2e12, link_bytes_per_s=2.4e11,
        dispatch_overhead_s=1.0e-4, hbm_bytes=float(32 << 30),
        source="tpu-v4"),
    "tpu-v5e": PeakTable(
        flops=1.97e14, hbm_bytes_per_s=8.1e11, link_bytes_per_s=1.6e11,
        dispatch_overhead_s=1.0e-4, hbm_bytes=float(16 << 30),
        source="tpu-v5e"),
    "tpu-v3": PeakTable(
        flops=1.23e14, hbm_bytes_per_s=9.0e11, link_bytes_per_s=1.4e11,
        dispatch_overhead_s=1.0e-4, hbm_bytes=float(16 << 30),
        source="tpu-v3"),
    "tpu-v2": PeakTable(
        flops=4.5e13, hbm_bytes_per_s=7.0e11, link_bytes_per_s=1.0e11,
        dispatch_overhead_s=1.0e-4, hbm_bytes=float(8 << 30),
        source="tpu-v2"),
}


def resolve_peaks(platform: str | None = None,
                  device_kind: str | None = None) -> PeakTable:
    """The ceilings for this process: per-platform table entry (device
    kind beats bare platform — "TPU v4" maps to the v4 row), then the
    CPU-calibrated default, with ``ZOO_ORACLE_PEAKS`` (JSON object)
    overriding individual fields last.  Unknown keys in the override
    are rejected loudly — a typo'd ceiling must not silently leave the
    default in place."""
    table = PLATFORM_PEAKS["cpu"]
    kind = (device_kind or platform or "cpu").lower().replace(" ", "-")
    for key, peaks in PLATFORM_PEAKS.items():
        if key != "cpu" and (key in kind or kind in key):
            table = peaks
            break
    else:
        if kind.startswith("tpu"):
            table = PLATFORM_PEAKS["tpu-v4"]
    raw = os.environ.get("ZOO_ORACLE_PEAKS")
    if not raw:
        return table
    try:
        override = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"ZOO_ORACLE_PEAKS must be a JSON object of PeakTable "
            f"fields: {e}") from e
    if not isinstance(override, dict):
        raise ValueError(
            f"ZOO_ORACLE_PEAKS must be a JSON object, got "
            f"{type(override).__name__}")
    fields = {f.name for f in dataclasses.fields(PeakTable)}
    unknown = set(override) - fields
    if unknown:
        raise ValueError(
            f"ZOO_ORACLE_PEAKS: unknown field(s) {sorted(unknown)}; "
            f"valid: {sorted(fields - {'source'})}")
    merged = {**table.to_doc(), **{
        k: (float(v) if k != "source" else str(v))
        for k, v in override.items()}}
    merged["source"] = str(override.get("source", "env"))
    return PeakTable(**merged)


# ---------------------------------------------------------------------------
# Roofline prediction.
# ---------------------------------------------------------------------------

_FEATURE_ALIASES = {
    "matmul_flops": ("matmul_flops", "flops", "zoo_hlo_flops"),
    "bytes_accessed": ("bytes_accessed", "zoo_hlo_bytes_accessed"),
    "collective_bytes": ("collective_bytes", "zoo_hlo_collective_bytes"),
    "collective_count": ("collective_count", "zoo_hlo_collectives"),
    "fused_dispatch_count": ("fused_dispatch_count",
                             "zoo_hlo_fused_dispatches"),
    "op_count": ("op_count", "zoo_hlo_ops"),
    "async_collective_count": ("async_collective_count",
                               "zoo_hlo_async_collectives"),
    "overlapped_collective_bytes": ("overlapped_collective_bytes",
                                    "zoo_hlo_overlapped_collective_bytes"),
}


def normalize_features(features: Mapping) -> dict:
    """Canonical feature dict from any of the shapes the repo emits:
    :meth:`HloReport.features`, a ``zoo_hlo_*``-prefixed metrics
    scrape, or a BENCH_*.json ``hlo`` block.  Missing keys become 0 —
    a v1 report with nulls still yields a usable vector."""
    out = {}
    for canon, names in _FEATURE_ALIASES.items():
        val = 0
        for name in names:
            got = features.get(name)
            if got is not None:
                val = got
                break
        out[canon] = float(val)
    return out


#: fraction of a plan's collective seconds that stays EXPOSED (serial
#: with compute) per overlap mode.  Serial plans expose everything —
#: the pre-overlap additive roofline exactly.  Bucketed "+overlap"
#: plans hide all but the tail: the last gradient bucket's
#: reduce-scatter has no backward segment left to hide behind, and the
#: first prefetch gather precedes any compute — validated against the
#: measured serial/bucketed legs in BENCH_OVERLAP_r13.json.
EXPOSED_FRACTIONS = {"serial": 1.0, "overlap": 0.25}


#: Per-dtype ceiling factors relative to the f32 row of a
#: :class:`PeakTable` — the precision plane's roofline terms:
#: ``flops`` multiplies the matmul ceiling (TPU MXUs run bf16 at ~2× the
#: f32 rate and int8 at ~2× bf16; the CPU backend shows no such win, but
#: the RANKING the oracle needs is the TPU one — the CPU-tier benches
#: assert bytes/feature deltas, not throughput), ``bytes`` is the
#: element-size ratio (what a compute-copy collective or activation
#: weighs against its f32 twin).
DTYPE_PEAK_FACTORS = {
    None: {"flops": 1.0, "bytes": 1.0},
    "f32": {"flops": 1.0, "bytes": 1.0},
    "bf16": {"flops": 2.0, "bytes": 0.5},
    "f16": {"flops": 2.0, "bytes": 0.5},
    "int8": {"flops": 4.0, "bytes": 0.25},
}


def _dtype_factors(dtype: str | None) -> dict:
    try:
        return DTYPE_PEAK_FACTORS[dtype]
    except KeyError:
        raise ValueError(
            f"unknown compute dtype {dtype!r}; valid: "
            f"{', '.join(str(k) for k in DTYPE_PEAK_FACTORS)}") from None


def plan_dtype(plan: str | None) -> str | None:
    """Compute-dtype segment of a plan/config name (``"fsdp+bf16"`` →
    ``"bf16"``; :func:`~analytics_zoo_tpu.parallel.plan.with_dtype`
    naming), ``None`` when the name declares no precision variant."""
    if plan is None:
        return None
    for seg in str(plan).split("+")[1:]:
        if seg in ("bf16", "f16", "int8"):
            return seg
    return None


def dtype_peaks(peaks: PeakTable, dtype: str | None) -> PeakTable:
    """A :class:`PeakTable` with the matmul ceiling scaled for a compute
    dtype (:data:`DTYPE_PEAK_FACTORS` — bf16 doubles the f32 rate, int8
    doubles it again); ``None``/``"f32"`` return ``peaks`` unchanged."""
    f = _dtype_factors(dtype)["flops"]
    if f == 1.0:
        return peaks
    return dataclasses.replace(peaks, flops=peaks.flops * f,
                               source=f"{peaks.source}+{dtype}")


def histogram_compute_dtype(dtype_histogram: Mapping | None) -> str | None:
    """Dominant floating compute dtype of a zoo-hlo-report/2
    ``dtype_histogram`` — the MEASURED confirmation that a dtype policy
    actually lowered (a bf16_mixed program's histogram shifts from f32-
    to bf16-majority), and the dtype the roofline ceilings should use
    when predicting from that program's features."""
    if not dtype_histogram:
        return None
    floats = {k: int(v) for k, v in dtype_histogram.items()
              if k in ("f32", "bf16", "f16") and v}
    if not floats:
        return None
    return max(floats, key=lambda k: (floats[k], k))


def plan_exposed_fraction(plan: str | None) -> float:
    """Exposed-collective fraction for a plan NAME: ``+overlap`` plans
    (bucketed grad scatter / gather prefetch) hide all but the tail
    bucket; every other plan serializes its collectives after the
    backward (fraction 1.0 — the old additive model)."""
    if plan is None:
        return EXPOSED_FRACTIONS["serial"]
    # segment match, not suffix: with_remat() composes names like
    # "fsdp+overlap+remat_full"
    return (EXPOSED_FRACTIONS["overlap"]
            if "overlap" in str(plan).split("+")
            else EXPOSED_FRACTIONS["serial"])


def predict_step_seconds(features: Mapping, k: int = 1,
                         peaks: PeakTable | None = None,
                         plan: str | None = None,
                         exposed_fraction: float | None = None,
                         dtype: str | None = None,
                         dtype_histogram: Mapping | None = None) -> float:
    """Overlap-aware roofline per-STEP wall seconds at
    ``steps_per_dispatch=k``:
    ``max(compute, memory, overlappable_collectives)
    + exposed_collectives + dispatch_overhead/k``.

    The max() is the classic roofline extended with the collective
    seconds a latency-hiding schedule can run CONCURRENTLY with
    compute; only the exposed remainder serializes after it.  The
    exposed fraction comes from (highest priority first) the
    ``exposed_fraction`` argument, the ``overlapped_collective_bytes``
    feature when the HLO actually contains async start/done pairs, or
    the plan name (:func:`plan_exposed_fraction` — serial plans expose
    1.0, which reproduces the pre-overlap additive model EXACTLY).  The
    overhead term is what K amortizes.

    The matmul ceiling is DTYPE-DEPENDENT (:func:`dtype_peaks`): the
    compute dtype comes from the ``dtype`` argument, else the program's
    measured ``dtype_histogram`` (zoo-hlo-report/2,
    :func:`histogram_compute_dtype`), else the plan name's precision
    segment (``"fsdp+bf16"``).  The byte features are NOT rescaled —
    they were extracted from the lowered program, which already counts
    its tensors at their true widths."""
    peaks = peaks if peaks is not None else resolve_peaks()
    if dtype is None:
        dtype = histogram_compute_dtype(dtype_histogram) \
            or plan_dtype(plan)
    peaks = dtype_peaks(peaks, dtype)
    f = normalize_features(features)
    compute_s = f["matmul_flops"] / max(peaks.flops, 1.0)
    memory_s = f["bytes_accessed"] / max(peaks.hbm_bytes_per_s, 1.0)
    collective_s = f["collective_bytes"] / max(peaks.link_bytes_per_s, 1.0)
    if exposed_fraction is None:
        overlapped = f["overlapped_collective_bytes"]
        if overlapped > 0 and f["collective_bytes"] > 0:
            exposed_fraction = 1.0 - overlapped / f["collective_bytes"]
        else:
            exposed_fraction = plan_exposed_fraction(plan)
    exposed_fraction = min(max(float(exposed_fraction), 0.0), 1.0)
    overlappable_s = collective_s * (1.0 - exposed_fraction)
    exposed_s = collective_s * exposed_fraction
    overhead_s = peaks.dispatch_overhead_s / max(int(k), 1)
    return max(compute_s, memory_s, overlappable_s) + exposed_s \
        + overhead_s


def predict_steps_per_sec(features: Mapping, k: int = 1,
                          peaks: PeakTable | None = None,
                          plan: str | None = None,
                          exposed_fraction: float | None = None,
                          dtype: str | None = None,
                          dtype_histogram: Mapping | None = None) -> float:
    """Inverse of :func:`predict_step_seconds`."""
    return 1.0 / max(
        predict_step_seconds(features, k=k, peaks=peaks, plan=plan,
                             exposed_fraction=exposed_fraction,
                             dtype=dtype,
                             dtype_histogram=dtype_histogram), 1e-12)


# ---------------------------------------------------------------------------
# Serving (predict-step) roofline — ISSUE 20, the TpuGraphs framing
# applied to inference: the per-bucket predict programs the
# InferenceModel compiles through timed_compile carry the same
# zoo_hlo_* feature vector as train steps, so the same roofline
# predicts their wall seconds BEFORE the first request.
# ---------------------------------------------------------------------------

#: compile-label prefix of the bucketed predict programs
#: (pipeline/inference/inference_model.py ``_get_compiled``)
SERVING_LABEL_PREFIX = "inference_b"


def serving_bucket_label(bucket: int) -> str:
    """The compile label ``InferenceModel`` stamps on the pad-bucket's
    predict program — the join key between a bucket's hlo report row
    and its measured predict seconds."""
    return f"{SERVING_LABEL_PREFIX}{int(bucket)}"


def predict_serving_seconds(features: Mapping, batch: int = 1,
                            peaks: PeakTable | None = None,
                            dtype: str | None = None,
                            dtype_histogram: Mapping | None = None,
                            ) -> float:
    """Roofline wall seconds for ONE dispatch of a bucketed predict
    program.

    ``features`` is the zoo_hlo_* vector of the PAD-BUCKET program
    (already sized for the padded batch); ``batch`` only matters when
    the features were extracted at a different bucket size — the
    compute/memory byte terms scale linearly with the batch dimension
    (activations dominate a forward pass), while the dispatch overhead
    is per-call and does not.  Serving dispatches are k=1 by
    construction (each request batch is one executable call — there is
    no multi-step fusion to amortize the overhead across), which is why
    the overhead term matters MORE here than in training: at small
    buckets it is the floor the pad-bucket set must respect."""
    peaks = peaks if peaks is not None else resolve_peaks()
    if dtype is None:
        dtype = histogram_compute_dtype(dtype_histogram)
    peaks = dtype_peaks(peaks, dtype)
    f = normalize_features(features)
    scale = max(float(batch), 1.0) / max(
        float(f.get("feature_batch") or batch or 1), 1.0)
    compute_s = scale * f["matmul_flops"] / max(peaks.flops, 1.0)
    memory_s = scale * f["bytes_accessed"] \
        / max(peaks.hbm_bytes_per_s, 1.0)
    collective_s = f["collective_bytes"] \
        / max(peaks.link_bytes_per_s, 1.0)
    return max(compute_s, memory_s) + collective_s \
        + peaks.dispatch_overhead_s


def load_serving_rows(report_dir: str) -> list[dict]:
    """The predict-labelled slice of :func:`load_report_rows`, keyed by
    pad bucket: one row per ``inference_b<bucket>`` report (latest file
    per label wins), with ``bucket`` parsed from the label or the
    stamped meta.  The serving oracle's feature source — empty until an
    :class:`InferenceModel` has compiled (or warmed) its buckets under
    ``ZOO_HLO_REPORT_DIR``."""
    by_label: dict[str, dict] = {}
    for row in load_report_rows(report_dir):
        label = str(row.get("label") or "")
        if not label.startswith(SERVING_LABEL_PREFIX):
            continue
        bucket = row.get("bucket")
        if bucket is None:
            suffix = label[len(SERVING_LABEL_PREFIX):]
            if not suffix.isdigit():
                continue
            bucket = int(suffix)
        row = dict(row)
        row["bucket"] = int(bucket)
        by_label[label] = row  # sorted read order: later files win
    return sorted(by_label.values(), key=lambda r: r["bucket"])


# ---------------------------------------------------------------------------
# Per-plan memory + interconnect models (the plan="auto" inputs).
# ---------------------------------------------------------------------------

#: (param_factor, opt_factor) of per-chip resident bytes as a fraction
#: of the global tree, for an n-way shard: dp replicates both, zero1
#: shards optimizer state only, zero2 adds the gradient reduce-scatter
#: (grads are transient in JAX, so PERSISTENT state matches zero1),
#: zero3/fsdp shard both, pipeline splits the stage-stacked tree over
#: the pipe axis, tp shards params + opt over the model axis
#: (rule-table dependent; 1/n is the intended steady state).  Matches
#: the live-array measurements in BENCH_PARTITION_r10.json (fsdp ≈
#: 0.125x on 8 devices) and BENCH_MEMORY_r12.json (zero3 ≈ 0.125x).
PLAN_MEMORY_FACTORS = {
    "dp": (1.0, 1.0),
    "zero1": (1.0, None),   # None -> 1/n
    "zero2": (1.0, None),
    "fsdp": (None, None),
    "zero3": (None, None),
    "pipeline": (None, None),
    "tp": (None, None),
}

#: fraction of the ACTIVATION estimate still resident under a remat
#: policy: full recomputes everything (only layer boundaries survive),
#: dots keeps contraction outputs, attn keeps only the tagged
#: attention context.
REMAT_ACTIVATION_FACTORS = {
    None: 1.0,
    "full": 0.15,
    "dots": 0.5,
    "attn": 0.35,
}

#: compute-time multiplier a remat policy costs (the recompute half of
#: the memory/FLOPs tradeoff): full remat replays the forward inside
#: the backward (~4/3 of baseline training FLOPs), partial policies
#: replay proportionally less.
REMAT_FLOPS_FACTORS = {
    None: 1.0,
    "full": 4.0 / 3.0,
    "dots": 1.15,
    "attn": 1.25,
}


def _plan_key(plan: str) -> str:
    """Normalize a plan name for table lookup: a ``+remat_*`` /
    ``+overlap`` suffix (``with_remat`` / ``overlap=`` naming) strips
    off, and every ``pipeline_<schedule>`` plan shares the ``pipeline``
    row."""
    base = str(plan).split("+", 1)[0]
    return "pipeline" if base.startswith("pipeline") else base


def predict_chip_bytes(param_bytes: int, opt_bytes: int, plan: str,
                       n_shards: int, batch_bytes: int = 0,
                       activation_bytes: int = 0,
                       remat: str | None = None,
                       dtype: str | None = None) -> int:
    """Predicted per-chip resident bytes under ``plan`` on an
    ``n_shards``-way mesh axis: the persistent param+opt footprint the
    sharding plan controls, plus the per-chip batch slice and — when an
    ``activation_bytes`` estimate is given — the activation residue the
    ``remat`` policy leaves live (:data:`REMAT_ACTIVATION_FACTORS`).

    ``dtype`` (or the plan name's precision segment) scales the
    ACTIVATION term only: under the precision plane's accumulation
    contract the stored params and optimizer state are f32 masters
    whatever the compute dtype, so their footprint is dtype-independent
    — the activations (and the transient compute copies they imply) are
    what bf16 halves."""
    if dtype is None:
        dtype = plan_dtype(plan)
    try:
        pf, of = PLAN_MEMORY_FACTORS[_plan_key(plan)]
    except KeyError:
        raise ValueError(
            f"unknown plan {plan!r}; valid: "
            f"{', '.join(sorted(PLAN_MEMORY_FACTORS))}") from None
    try:
        af = REMAT_ACTIVATION_FACTORS[remat]
    except KeyError:
        raise ValueError(
            f"unknown remat policy {remat!r}; valid: "
            f"{', '.join(str(k) for k in REMAT_ACTIVATION_FACTORS)}"
        ) from None
    n = max(int(n_shards), 1)
    pf = pf if pf is not None else 1.0 / n
    of = of if of is not None else 1.0 / n
    af *= _dtype_factors(dtype)["bytes"]
    return int(param_bytes * pf + opt_bytes * of
               + batch_bytes / n + activation_bytes * af)


#: the portion of a plan's collective coefficient that moves COMPUTE
#: copies (param all-gathers, forward+backward) rather than gradients —
#: under the f32-accumulation contract only this portion shrinks with
#: the compute dtype; gradient reduce-scatters / all-reduces stay f32.
_GATHER_COEFF = {"fsdp": 2.0, "zero3": 2.0}


def plan_collective_bytes(param_bytes: int, plan: str,
                          n_shards: int,
                          dtype: str | None = None) -> int:
    """Per-STEP interconnect bytes a plan moves for ``param_bytes`` of
    weights on an ``n_shards``-way axis (ring-collective accounting,
    2·P·(n-1)/n per all-reduce equivalent):

    - dp: one gradient all-reduce (2P);
    - zero1: reduce-scatter grads into the moment shards + all-gather
      the updates back (2P, plus the sharded update's gather skew —
      charged 2.5P so dp ranks strictly first at equal memory);
    - zero2: zero1's traffic plus the pinned gradient scatter's
      re-layout (2.6P, so zero1 ranks first at equal memory);
    - fsdp: all-gather params on use (forward AND backward) +
      reduce-scatter grads (3P);
    - zero3: fsdp's traffic with the explicit gradient-shard pin
      (3.1P, so fsdp ranks first at equal memory);
    - pipeline: stage-boundary ppermute traffic, activation-sized and
      model dependent — charged like dp's 2P as a neutral default;
    - tp: activation collectives, model/rule dependent — charged like
      dp's 2P as a neutral default.

    These coefficients exist to RANK plans (fewest collectives first at
    equal feasibility), not to predict absolute seconds; the residual
    model absorbs the constants once outcomes accumulate.

    ``dtype`` (or the plan name's precision segment) applies the
    accumulation contract: the param-GATHER portion of fsdp/zero3
    traffic (:data:`_GATHER_COEFF` — the all-gathers move compute
    copies) scales by the dtype's element-size ratio, while the
    gradient reduce-scatter / all-reduce portion stays f32 — so
    ``fsdp+bf16`` predicts 2/3 of fsdp's bytes, the measurable
    collective-bytes reduction the precision bench pins."""
    if dtype is None:
        dtype = plan_dtype(plan)
    n = max(int(n_shards), 1)
    if n <= 1:
        return 0
    ring = param_bytes * (n - 1) / n
    coeff = {"dp": 2.0, "zero1": 2.5, "zero2": 2.6, "fsdp": 3.0,
             "zero3": 3.1, "pipeline": 2.0, "tp": 2.0}
    key = _plan_key(plan)
    try:
        total = coeff[key]
    except KeyError:
        raise ValueError(
            f"unknown plan {plan!r}; valid: "
            f"{', '.join(sorted(coeff))}") from None
    gather = _GATHER_COEFF.get(key, 0.0)
    bytes_factor = _dtype_factors(dtype)["bytes"]
    return int((total - gather + gather * bytes_factor) * ring)


# ---------------------------------------------------------------------------
# Kernel plane: per-kernel analytic HBM byte terms.  Pallas kernels win
# by collapsing round trips, so the quantity that ranks kernel vs XLA is
# bytes touched, not FLOPs — and it is exactly the quantity the choke
# point MEASURES after lowering (HloReport.custom_kernel_bytes sums
# custom-call operand+result bytes).  Each "kernel" term below is that
# operand+result sum, which is why the bench can assert
# |measured - predicted| / predicted <= 0.05 rather than hand-waving.
# ---------------------------------------------------------------------------

#: Word counts behind the formulas (f32 = 4 bytes unless noted):
#:
#: - ``fused_adam``: one custom call moves g/mu/nu in and upd/mu'/nu'
#:   out (6 f32 arrays of padded size n) plus a (6,) SMEM scalar vector
#:   -> 24n + 24.  The unfused optax chain re-materializes mu, nu,
#:   mu_hat, nu_hat, the quotient and the lr scale as separate
#:   elementwise passes: 15 f32 words/element -> 60n.
#: - ``fused_softmax_xent``: logits + (B,1) int32 labels in, (B,1)
#:   loss + (B,1) lse out -> 4BV + 12B.  The XLA path writes the (B,V)
#:   log-prob tensor and reads it back for the gather: 3 passes over
#:   the big tensor -> 12BV (+ the same small per-row terms, dropped).
#: - ``int8_matmul``: weight-stationary — x (f32) + int8 weights +
#:   per-channel scales in, f32 out -> 4MK + KN + 4N + 4MN.  The
#:   dequantize-first path additionally writes AND reads the f32
#:   weight tensor -> 4MK + KN + 8KN + 4MN.
#: - ``flash``: q/k/v/o only -> 16·B·H·L·D; the dense path also writes
#:   and reads the (L,L) score matrix per head -> + 8·B·H·L².
KERNEL_BYTE_MODELS = ("fused_adam", "fused_softmax_xent", "int8_matmul",
                      "flash")


def kernel_bytes(kernel: str, **sizes) -> dict:
    """Analytic HBM bytes for one invocation of ``kernel`` vs its
    unfused XLA twin: ``{"kernel": bytes, "xla": bytes}``.

    Size kwargs per kernel: ``fused_adam(n)`` — padded element count;
    ``fused_softmax_xent(batch, vocab)``; ``int8_matmul(m, k, n)``;
    ``flash(batch, heads, seq, head_dim)``.  The "kernel" term is the
    custom call's operand+result byte sum — the same number
    ``HloReport.custom_kernel_bytes`` measures after TPU lowering."""
    if kernel == "fused_adam":
        n = float(sizes["n"])
        return {"kernel": 24.0 * n + 24.0, "xla": 60.0 * n}
    if kernel == "fused_softmax_xent":
        b, v = float(sizes["batch"]), float(sizes["vocab"])
        return {"kernel": 4.0 * b * v + 12.0 * b,
                "xla": 12.0 * b * v + 12.0 * b}
    if kernel == "int8_matmul":
        m, k, n = float(sizes["m"]), float(sizes["k"]), float(sizes["n"])
        io = 4.0 * m * k + k * n + 4.0 * m * n
        return {"kernel": io + 4.0 * n, "xla": io + 8.0 * k * n}
    if kernel == "flash":
        b, h = float(sizes["batch"]), float(sizes["heads"])
        l, d = float(sizes["seq"]), float(sizes["head_dim"])
        qkvo = 16.0 * b * h * l * d
        return {"kernel": qkvo, "xla": qkvo + 8.0 * b * h * l * l}
    raise ValueError(
        f"unknown kernel {kernel!r}; valid: "
        f"{', '.join(KERNEL_BYTE_MODELS)}")


def choose_kernel(kernel: str, platform: str | None = None,
                  peaks: PeakTable | None = None, **sizes) -> dict:
    """Kernel-vs-XLA verdict for one scope on one platform.

    Platform gates first: Pallas lowers through Mosaic, so any
    non-TPU platform picks ``"xla"`` regardless of the byte model —
    this is the oracle DECLINING the kernel on the CPU tier, not a
    failure.  On TPU the pick is the smaller analytic byte term, with
    per-variant seconds at the platform's HBM ceiling recorded so the
    verdict doc ranks like the roofline does."""
    predicted = kernel_bytes(kernel, **sizes)
    if peaks is None:
        peaks = resolve_peaks(platform)
    bw = float(peaks.hbm_bytes_per_s)
    doc = {
        "kernel": kernel,
        "platform": platform or "cpu",
        "sizes": {k: int(v) for k, v in sizes.items()},
        "predicted_bytes": {k: int(v) for k, v in predicted.items()},
        "predicted_s": {k: v / bw for k, v in predicted.items()},
        "peaks_source": peaks.source,
    }
    on_tpu = str(platform or "cpu").lower().startswith("tpu")
    if not on_tpu:
        doc["choice"] = "xla"
        doc["reason"] = ("pallas kernels lower via Mosaic (TPU only); "
                         "the jnp fallback on this platform is the "
                         "same XLA program")
    elif predicted["kernel"] < predicted["xla"]:
        doc["choice"] = kernel
        saved = predicted["xla"] - predicted["kernel"]
        doc["reason"] = (f"kernel saves {int(saved)} HBM bytes/step "
                         f"({predicted['kernel'] / predicted['xla']:.2f}x "
                         f"of the unfused traffic)")
    else:
        doc["choice"] = "xla"
        doc["reason"] = ("analytic byte model predicts no HBM win at "
                         "these sizes")
    return doc


# ---------------------------------------------------------------------------
# The fitted residual: least squares over log-space features, stdlib
# only.  target = log(measured_sps) - log(analytic_sps); prediction
# multiplies the analytic roofline by exp(w·x).
# ---------------------------------------------------------------------------


def _residual_vector(features: Mapping, k: int) -> list[float]:
    f = normalize_features(features)
    return [
        1.0,
        math.log1p(f["matmul_flops"]),
        math.log1p(f["bytes_accessed"]),
        math.log1p(f["collective_bytes"]),
        math.log(max(int(k), 1)),
        math.log1p(f["op_count"]),
    ]


def _solve_ridge(rows: Sequence[Sequence[float]],
                 targets: Sequence[float],
                 lam: float = 1e-3) -> list[float]:
    """(AᵀA + λI) w = Aᵀb by Gaussian elimination with partial
    pivoting — six unknowns, so O(d³) in pure Python is microseconds.
    The ridge term keeps the system nonsingular when every sample
    shares a feature value (one model swept over K alone)."""
    d = len(rows[0])
    ata = [[lam if i == j else 0.0 for j in range(d)] for i in range(d)]
    atb = [0.0] * d
    for row, t in zip(rows, targets):
        for i in range(d):
            atb[i] += row[i] * t
            for j in range(d):
                ata[i][j] += row[i] * row[j]
    # augmented elimination
    for col in range(d):
        pivot = max(range(col, d), key=lambda r: abs(ata[r][col]))
        if abs(ata[pivot][col]) < 1e-12:
            continue
        ata[col], ata[pivot] = ata[pivot], ata[col]
        atb[col], atb[pivot] = atb[pivot], atb[col]
        inv = 1.0 / ata[col][col]
        for r in range(d):
            if r == col:
                continue
            factor = ata[r][col] * inv
            if factor == 0.0:
                continue
            for c in range(col, d):
                ata[r][c] -= factor * ata[col][c]
            atb[r] -= factor * atb[col]
    return [atb[i] / ata[i][i] if abs(ata[i][i]) > 1e-12 else 0.0
            for i in range(d)]


class ResidualModel:
    """Multiplicative correction to the analytic roofline, fitted from
    accumulated (features, K, measured steps/sec) rows.

    ``ready`` stays False below ``min_samples`` rows (or before any
    :meth:`fit`): callers must then use the analytic prediction alone —
    :meth:`predict_steps_per_sec` does exactly that, so the zero-data
    path needs no branching at call sites."""

    def __init__(self, peaks: PeakTable | None = None,
                 min_samples: int = MIN_FIT_SAMPLES):
        self.peaks = peaks if peaks is not None else resolve_peaks()
        self.min_samples = int(min_samples)
        self.weights: list[float] | None = None
        self.n_samples = 0

    @property
    def ready(self) -> bool:
        return self.weights is not None

    def fit(self, rows: Iterable[Mapping]) -> "ResidualModel":
        """``rows``: dicts with ``features`` (any alias shape), ``k``
        and ``measured_steps_per_sec``.  Rows without a positive
        measurement are dropped; below ``min_samples`` survivors the
        model stays analytic (``ready`` False)."""
        xs, ts = [], []
        for row in rows:
            sps = row.get("measured_steps_per_sec") or 0
            if sps <= 0:
                continue
            feats = row.get("features") or {}
            k = int(row.get("k") or 1)
            analytic = predict_steps_per_sec(feats, k=k, peaks=self.peaks)
            xs.append(_residual_vector(feats, k))
            ts.append(math.log(sps) - math.log(analytic))
        self.n_samples = len(xs)
        if self.n_samples < self.min_samples:
            self.weights = None
            return self
        self.weights = _solve_ridge(xs, ts)
        return self

    def predict_steps_per_sec(self, features: Mapping, k: int = 1) -> float:
        analytic = predict_steps_per_sec(features, k=k, peaks=self.peaks)
        if self.weights is None:
            return analytic
        x = _residual_vector(features, k)
        log_corr = sum(w * xi for w, xi in zip(self.weights, x))
        # clamp the correction: an extrapolated fit must dent the
        # analytic prediction, not replace it with nonsense
        log_corr = max(-3.0, min(3.0, log_corr))
        return analytic * math.exp(log_corr)


# ---------------------------------------------------------------------------
# Training-row loaders: the data loop's read side.
# ---------------------------------------------------------------------------


def load_report_rows(report_dir: str) -> list[dict]:
    """``ZOO_HLO_REPORT_DIR`` reports as feature rows.  Accepts schema
    ``zoo-hlo-report/1`` (no plan/mesh/K/compile-seconds — those fields
    come back None) alongside v2; unparseable files are skipped, never
    raised."""
    rows = []
    try:
        names = sorted(os.listdir(report_dir))
    except OSError:
        return rows
    for name in names:
        if not (name.startswith("hlo-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(report_dir, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not str(doc.get("schema", "")).startswith("zoo-hlo-report/"):
            continue
        rows.append({
            "label": doc.get("label"),
            "features": normalize_features(doc.get("features") or {}),
            "k": doc.get("steps_per_dispatch"),
            "plan": doc.get("plan"),
            "mesh_shape": doc.get("mesh_shape"),
            "compile_seconds": doc.get("compile_seconds"),
            "dtype_histogram": doc.get("dtype_histogram"),
            "dtype_policy": doc.get("dtype_policy"),
            "bucket": doc.get("bucket"),
            "ts": doc.get("ts"),
        })
    return rows


def load_bench_rows(bench_dir: str) -> list[dict]:
    """Measured (features, K, steps/sec) rows from accumulated
    BENCH_*.json artifacts.  Only self-contained rows are harvested —
    today the partition bench's per-plan legs, which carry their own
    ``zoo_hlo_*`` feature block next to the measured steps/sec."""
    rows = []
    try:
        names = sorted(os.listdir(bench_dir))
    except OSError:
        return rows
    for name in names:
        if not (name.startswith("BENCH_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(bench_dir, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for leg in (doc.get("legs") or {}).values():
            hlo = leg.get("hlo") or {}
            sps = leg.get("steps_per_sec")
            if not hlo or not sps:
                continue
            rows.append({
                "label": f"{name}:{leg.get('plan')}",
                "features": normalize_features(hlo),
                "k": 1,
                "plan": leg.get("plan"),
                "measured_steps_per_sec": float(sps),
            })
    return rows


def load_tune_log_rows(tune_log_dir: str) -> list[dict]:
    """Measured per-K rows from the autotuner's persisted decision
    history (``ZOO_TUNE_LOG_DIR`` JSONL, feature/autotune.py): each
    ``settle`` record carries the full measured cost curve
    ``k_cost_per_step_s`` under the program's compile label — joined
    with a report row's features by that label, each (K, cost) pair
    becomes a training sample."""
    rows = []
    try:
        names = sorted(os.listdir(tune_log_dir))
    except OSError:
        return rows
    for name in names:
        if ".jsonl" not in name:
            continue
        try:
            with open(os.path.join(tune_log_dir, name)) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") != "settle":
                continue
            for k, cost in (rec.get("k_cost_per_step_s") or {}).items():
                if not cost or float(cost) <= 0:
                    continue
                rows.append({
                    "label": rec.get("label"),
                    "k": int(k),
                    "measured_steps_per_sec": 1.0 / float(cost),
                })
    return rows


def training_rows(report_dir: str | None = None,
                  bench_dir: str | None = None,
                  tune_log_dir: str | None = None) -> list[dict]:
    """The residual model's joined training set.  Bench legs are
    self-contained; tune-log rows (measurement, no features) join with
    the latest report row of the same compile label (features, no
    measurement).  Unjoinable rows drop silently — with nothing
    accumulated yet the result is [] and the caller's fit stays
    analytic."""
    report_dir = report_dir or os.environ.get("ZOO_HLO_REPORT_DIR")
    tune_log_dir = tune_log_dir or os.environ.get("ZOO_TUNE_LOG_DIR")
    rows = list(load_bench_rows(bench_dir)) if bench_dir else []
    reports = load_report_rows(report_dir) if report_dir else []
    by_label: dict[str, dict] = {}
    for rpt in reports:  # later files win: freshest features per label
        if rpt.get("label"):
            by_label[rpt["label"]] = rpt
    for rec in (load_tune_log_rows(tune_log_dir) if tune_log_dir else []):
        rpt = by_label.get(rec.get("label"))
        if rpt is None:
            continue
        rows.append({**rec, "features": rpt["features"],
                     "plan": rpt.get("plan")})
    return rows
