"""Closed-loop autotuning of the host data plane and fused dispatch.

PRs 3 and 4 made the hot paths fast but HAND-tuned: ``ZOO_PREFETCH_WORKERS``
/ ``ZOO_PREFETCH_DEPTH`` and ``ZOO_STEPS_PER_DISPATCH=K`` are static knobs
that must be re-swept per model, per host, per input pipeline.  tf.data
(PAPERS.md, arxiv 2101.12127) showed that a controller driven by the
pipeline's own telemetry matches or beats hand tuning; TpuGraphs (arxiv
2308.13490) frames config choice as prediction from measured features.
Every signal needed is already exported — this module closes the loop:

- :class:`AutotuneController` runs on a daemon thread reading
  ROLLING-WINDOW deltas (``Histogram.delta_since``) of the
  ``zoo_data_prefetch_*`` telemetry and online-resizes the live
  :class:`~analytics_zoo_tpu.feature.prefetch.PrefetchPipeline` — worker
  pool, bounded queue depth, and shard read-ahead — driving consumer-wait
  p50 → 0 under a host-RAM budget (``ZOO_AUTOTUNE_RAM_BUDGET``, estimated
  from observed batch/shard byte sizes x window size).  Resizes are
  in-place (no drain), so the delivered stream stays byte-identical
  through every decision.
- The same controller picks ``steps_per_dispatch`` K at dispatch
  boundaries: the estimator feeds it measured per-dispatch wall time
  (:meth:`AutotuneController.observe_dispatch`) and it hill-climbs over
  ``{1, 2, 4, 8, 16}``, settling on the smallest K within a few percent
  of the best per-step time.  Safe to explore online: per-inner-step RNG
  folds on the GLOBAL step index, so the loss trajectory is bit-identical
  regardless of the K sequence (the PR-4 contract).
- With a :class:`~analytics_zoo_tpu.analysis.oracle.ConfigOracle`
  attached (``oracle=`` / :meth:`from_config` under ``ZOO_ORACLE``,
  the default), the hill-climb starts from PREDICTION instead of from
  K=1: after the first compiled dispatch the controller reads the
  program's HLO features, jumps to the oracle's predicted K, and
  demotes the ladder sweep to a ±1-neighbor validation pass — ≤8
  dispatches to settle instead of ~53 (BENCH_ORACLE_r11 vs
  BENCH_AUTOTUNE_r08), same bitwise trajectory.  The settle outcome
  feeds back to the oracle (predicted-vs-measured), closing the loop.

Every decision is recorded three ways so a bad tune is diagnosable
post-mortem: the ``zoo_autotune_*`` metric family (current knob gauges +
a decision counter labeled knob/reason), an ``autotune`` flight-recorder
event, and a bounded structured decision log served at ``/varz`` (and
rendered as a table by ``tools/metrics_dump.py``).  Set
``ZOO_TUNE_LOG_DIR`` to additionally PERSIST the log as JSONL (one
``decision`` record per knob change + one ``settle`` record carrying
the full measured per-K cost curve; size-capped via
``ZOO_TUNE_LOG_MAX_BYTES`` with one rotated predecessor) — the decision
history the oracle's residual model trains on across restarts.

Opt-in: ``ZOO_AUTOTUNE=1`` (or ``Estimator.train(..., autotune=True)``).
Unset, nothing here is imported, no thread exists, and the hot paths are
exactly the static-knob code (pinned by test, the ``ZOO_SAN`` /
``ZOO_METRICS`` disabled-mode pattern).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import weakref

from analytics_zoo_tpu.metrics import (
    AutotuneMetrics,
    DataPipelineMetrics,
    MetricsRegistry,
    get_flight_recorder,
    get_registry,
)

__all__ = ["AutotuneController", "K_CANDIDATES", "DEFAULT_RAM_BUDGET",
           "varz_doc"]

# The fused-dispatch search space: beyond K=16 the per-dispatch overhead
# is already amortized to noise (BENCH_DISPATCH_r07: K=16 = 6.3x K=1)
# while checkpoint/validation cadence coarsens linearly.
K_CANDIDATES = (1, 2, 4, 8, 16)

# Default host-RAM budget for the prefetch window (batches in the queue +
# in-flight transforms + read-ahead shards): 2 GiB — generous for batch
# streams, conservative next to a training host's total RAM.
DEFAULT_RAM_BUDGET = 2 << 30

# ---------------------------------------------------------------------------
# Live-controller registry: /varz (metrics/http.py) includes the decision
# logs of whatever controllers exist, WITHOUT importing this module into
# metrics-only processes — http.py only consults sys.modules.
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: "weakref.WeakSet[AutotuneController]" = (  # guarded-by: _active_lock
    weakref.WeakSet())

# ---------------------------------------------------------------------------
# Persistent decision log (ZOO_TUNE_LOG_DIR): the in-memory bounded log
# survives only until process exit — this JSONL file is the outcome
# history the config oracle's residual model trains on across restarts.
# ---------------------------------------------------------------------------

DEFAULT_TUNE_LOG_MAX_BYTES = 4 << 20

_tune_log_lock = threading.Lock()


def _append_tune_log(record: dict) -> None:
    """Append one JSONL record to ``ZOO_TUNE_LOG_DIR/tune-<pid>.jsonl``
    (no-op when the env is unset).  Size-capped: past
    ``ZOO_TUNE_LOG_MAX_BYTES`` the file rotates to ``.1`` (one
    predecessor kept) so an always-on training job cannot grow the log
    unboundedly.  Best-effort — a full disk must never take tuning
    down."""
    log_dir = os.environ.get("ZOO_TUNE_LOG_DIR")
    if not log_dir:
        return
    try:
        line = json.dumps(record) + "\n"
        cap = int(os.environ.get("ZOO_TUNE_LOG_MAX_BYTES",
                                 DEFAULT_TUNE_LOG_MAX_BYTES))
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, f"tune-{os.getpid()}.jsonl")
        with _tune_log_lock:
            try:
                if os.path.getsize(path) + len(line) > cap:
                    os.replace(path, path + ".1")
            except OSError:
                pass  # no file yet, or rotation raced a cleanup
            with open(path, "a") as f:
                f.write(line)
    except (OSError, ValueError, TypeError):
        return


def varz_doc() -> dict:
    """The ``autotune`` section of ``/varz``: every live controller's
    current knob state plus the merged, time-ordered decision log."""
    with _active_lock:
        ctrls = list(_active)
    docs = [c.to_doc() for c in ctrls]
    decisions = sorted((d for doc in docs for d in doc["decisions"]),
                       key=lambda d: d["ts"])
    return {"controllers": docs, "decisions": decisions}


class AutotuneController:
    """Telemetry-driven controller for the prefetch pipeline and fused
    dispatch.

    One controller serves one training/ingest loop.  Attach points:

    - ``PrefetchFeatureSet(..., controller=c)`` hands it each epoch's
      live pipeline (and the underlying :class:`ShardedFeatureSet`, when
      there is one) — the controller's thread then resizes workers /
      depth / read-ahead between telemetry windows, and re-seeds the
      next epoch's pipeline with the tuned values.
    - the estimator calls :meth:`observe_dispatch` once per jitted
      dispatch and :meth:`current_k` at chunk boundaries — the K
      hill-climb runs inline on those calls (no extra thread work).

    The thread starts lazily on the first pipeline attach (or an
    explicit :meth:`start`); :meth:`stop` joins it.  All tuned state
    survives pipeline re-creation, so convergence accumulates across
    epochs.
    """

    def __init__(self, ram_budget: int | None = None,
                 interval: float = 0.25,
                 min_window: int = 8,
                 wait_threshold_s: float = 1e-3,
                 max_workers: int | None = None,
                 max_depth: int = 64,
                 max_read_ahead: int = 4,
                 start_k: int = 1,
                 k_candidates=K_CANDIDATES,
                 k_samples: int = 6,
                 k_warm_skip: int = 3,
                 k_margin: float = 0.05,
                 registry: MetricsRegistry | None = None,
                 log_capacity: int = 256,
                 oracle=None,
                 k_prior_warm_skip: int = 1,
                 k_prior_samples: int = 2):
        self.ram_budget = int(ram_budget) if ram_budget else \
            DEFAULT_RAM_BUDGET
        self.interval = float(interval)
        self.min_window = int(min_window)
        self.wait_threshold_s = float(wait_threshold_s)
        # Default worker cap: NOT the core count — prefetch workers
        # scale GIL-releasing IO/decode (PR 3 measured 3.3x with 4
        # workers on a 1-core host), so cores only floor the cap.
        self.max_workers = int(max_workers) if max_workers else \
            min(8, 4 * (os.cpu_count() or 1))
        self.max_depth = int(max_depth)
        self.max_read_ahead = int(max_read_ahead)
        self.k_samples = int(k_samples)
        self.k_warm_skip = int(k_warm_skip)
        self.k_margin = float(k_margin)
        # oracle prior (analysis/oracle.py): when attached, the first
        # observed dispatch consults it and the sweep becomes a ±1
        # validation pass with a TIGHTER measurement window — the
        # prediction already absorbed the risk a long window hedges
        self.oracle = oracle
        self.k_prior_warm_skip = int(k_prior_warm_skip)
        self.k_prior_samples = int(k_prior_samples)
        cands = sorted(set(int(k) for k in k_candidates) | {int(start_k)})
        self.k_candidates = tuple(cands)

        # zoo_autotune_* family lives in the PROCESS registry (NULL
        # children when ZOO_METRICS=0 — decisions still log internally);
        # the PIPELINE telemetry the policy reads must exist even with
        # metrics globally off, so fall back to a private registry then.
        self.metrics = AutotuneMetrics(registry=registry)
        reg = registry if registry is not None else get_registry()
        if not reg.enabled:
            reg = MetricsRegistry(enabled=True)
        self.data_metrics = DataPipelineMetrics(registry=reg)

        self._lock = threading.Lock()
        # tuned pipeline knobs; None until the first pipeline_config
        # seeds them from the starting configuration
        self.workers: int | None = None  # guarded-by: _lock
        self.depth: int | None = None  # guarded-by: _lock
        self.read_ahead = 1  # guarded-by: _lock
        # live handles (one epoch's pipeline; cleared on detach)
        self._pipe = None  # guarded-by: _lock
        self._sharded = None  # guarded-by: _lock
        # rolling-window baseline (Histogram.snapshot_state tuple)
        self._wait_base = None  # guarded-by: _lock
        # K hill-climb state
        self._k = int(start_k)  # guarded-by: _lock
        self._k_settled = False  # guarded-by: _lock
        # prior-mode state: the compile label whose HLO features feed
        # the oracle, whether the prior was consulted yet, and the
        # remaining validation candidates (None = blind hill-climb)
        self._feature_label: str | None = None  # guarded-by: _lock
        self._prior_consulted = False  # guarded-by: _lock
        self._k_validate: list | None = None  # guarded-by: _lock
        self._k_prior_hint: int | None = None  # guarded-by: _lock
        self._k_skip: dict[int, int] = {}  # guarded-by: _lock
        self._k_times: dict[int, list] = {}  # guarded-by: _lock
        self._k_cost: dict[int, float] = {}  # guarded-by: _lock
        self.dispatches_observed = 0  # guarded-by: _lock
        # dispatches observed AT the tuner's current K — in-flight
        # chunks queued before a switch keep their old size (see
        # _chunk_batches_dynamic) and are pipeline latency, not tuning
        # observations; k_settle_dispatch counts search cost only
        self.tuning_dispatches = 0  # guarded-by: _lock
        self.k_settle_dispatch: int | None = None  # guarded-by: _lock
        self._decisions: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=int(log_capacity)))
        self._thread: threading.Thread | None = None  # guarded-by: _lock
        self._stop = threading.Event()

        self.metrics.ram_budget.set(self.ram_budget)
        self.metrics.k.set(self._k)
        self.metrics.read_ahead.set(self.read_ahead)
        with _active_lock:
            _active.add(self)

    # ------------------------------------------------------------------
    # construction from the env tier
    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, oracle=None) -> "AutotuneController":
        """Build from a :class:`~analytics_zoo_tpu.common.engine.ZooConfig`
        (the ``ZOO_AUTOTUNE_*`` env tier).  Unless ``ZOO_ORACLE=0`` (or
        an explicit ``oracle`` is given), a
        :class:`~analytics_zoo_tpu.analysis.oracle.ConfigOracle` is
        built from the env so the K search starts from prediction."""
        if oracle is None:
            try:
                from analytics_zoo_tpu.analysis.oracle import (
                    ConfigOracle,
                    oracle_enabled,
                )

                if oracle_enabled():
                    oracle = ConfigOracle.from_env()
            except Exception:  # a broken prior must never block tuning
                oracle = None
        return cls(
            ram_budget=cfg.autotune_ram_budget,
            interval=cfg.autotune_interval,
            max_workers=cfg.autotune_max_workers,
            start_k=int(cfg.steps_per_dispatch or 1),
            oracle=oracle,
        )

    def set_feature_label(self, label: str) -> None:
        """Name the compile label whose HLO features the oracle prior
        reads (the estimator calls this with the train step's label
        once the plan/K tag is known)."""
        with self._lock:
            self._feature_label = str(label)

    # ------------------------------------------------------------------
    # pipeline attachment (PrefetchFeatureSet.batches)
    # ------------------------------------------------------------------
    def pipeline_config(self, workers: int, depth: int) -> tuple[int, int]:
        """The (workers, depth) the NEXT pipeline should start with:
        the caller's values on first use (seeding the tuned state),
        the tuned values afterwards."""
        with self._lock:
            if self.workers is None:
                self.workers = max(1, int(workers))
                self.depth = max(1, int(depth))
            return self.workers, self.depth

    def attach_pipeline(self, pipe, sharded=None) -> None:
        """Hand the controller one epoch's LIVE pipeline (and sharded
        source, for the read-ahead knob); re-baselines the telemetry
        window and lazily starts the control thread."""
        with self._lock:
            self._pipe = pipe
            self._sharded = sharded
            self._wait_base = None
            ahead = self.read_ahead
        if sharded is not None and ahead > 1:
            sharded.set_read_ahead_count(ahead)
        self.start()

    def detach_pipeline(self, pipe) -> None:
        with self._lock:
            if self._pipe is pipe:
                self._pipe = None
                self._sharded = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "AutotuneController":
        # the Event is internally synchronized; clear it outside the
        # controller lock (it is not controller state the lock guards)
        self._stop.clear()
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="zoo-autotune")
            t = self._thread
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:
                # the controller must never take the training loop down;
                # a policy bug shows in the flight ring, not a crash
                get_flight_recorder().record_exception(e, where="autotune")

    # ------------------------------------------------------------------
    # the data-plane control loop (one tick per interval)
    # ------------------------------------------------------------------
    def _tick(self):
        with self._lock:
            pipe, sharded = self._pipe, self._sharded
            wait_base = self._wait_base
            read_ahead = self.read_ahead
        if pipe is None:
            return
        # seed tuned state from the live pipeline when attached directly
        # (PrefetchFeatureSet seeds via pipeline_config before attach)
        self.pipeline_config(pipe.workers, pipe.depth)
        m = pipe.metrics
        # the policy steers on consumer-wait alone; producer-stall stays
        # an operator diagnosis signal (observability.md) — no delta is
        # computed for it here, the control loop would only discard it
        wait = m.consumer_wait.delta_since(wait_base)
        new_wait_base = m.consumer_wait.snapshot_state()
        if wait_base is None:
            # first sight of this pipeline: establish the baseline only
            with self._lock:
                self._wait_base = new_wait_base
            return
        batch_bytes = int(m.batch_bytes.get())
        shard_bytes = int(sharded.last_shard_nbytes) if sharded is not None \
            else 0
        workers, depth = pipe.workers, pipe.depth
        estimate = batch_bytes * (depth + workers) + shard_bytes * read_ahead
        self.metrics.ram_estimate.set(estimate)

        if estimate > self.ram_budget and batch_bytes > 0:
            # hard constraint first: shed window until under budget
            target_depth = max(
                1, (self.ram_budget - shard_bytes * read_ahead)
                // batch_bytes - workers)
            target_depth = min(depth, target_depth)
            new_ahead = 1 if shard_bytes * read_ahead > self.ram_budget // 4 \
                else read_ahead
            self._consume_window(new_wait_base)
            self._apply(pipe, sharded, depth=target_depth,
                        read_ahead=new_ahead, reason="ram_budget")
            return

        if wait["count"] < self.min_window:
            return  # window too thin to act on; let it keep accumulating

        self._consume_window(new_wait_base)
        if wait["p50"] > self.wait_threshold_s:
            # the consumer is starving: the pipeline is the bottleneck.
            # Grow production (workers), the absorbing buffer (depth, up
            # to what the RAM budget allows), and — for sharded sources —
            # the shard read-ahead, then re-measure next window.
            new_workers = min(workers * 2, self.max_workers)
            # depth target: enough buffer to keep every worker busy and
            # absorb load bursts (~2x the pool), bounded by the RAM
            # budget — a starving consumer is a throughput problem more
            # depth alone cannot fix, so depth tracks workers instead of
            # running away to max_depth.
            depth_cap = min(self.max_depth, max(4, 2 * new_workers))
            if batch_bytes > 0:
                depth_cap = min(depth_cap, max(
                    1, (self.ram_budget - shard_bytes * read_ahead)
                    // batch_bytes - new_workers))
            new_depth = min(max(depth * 2, new_workers + 1), depth_cap)
            new_depth = max(new_depth, depth)  # never shrink on this path
            new_ahead = read_ahead
            if sharded is not None and read_ahead < self.max_read_ahead:
                if shard_bytes * (read_ahead + 1) + batch_bytes * \
                        (new_depth + new_workers) <= self.ram_budget:
                    new_ahead = read_ahead + 1
            self._apply(pipe, sharded, workers=new_workers,
                        depth=new_depth, read_ahead=new_ahead,
                        reason="consumer_wait")
        # else: consumer-wait p50 is ~0 — the goal state.  A fat
        # producer-stall p50 here means the DEVICE is the bottleneck and
        # the pipeline is keeping up; deliberately no shrink (idle pool
        # threads are near-free, and shrink/grow cycles would oscillate).

    def _consume_window(self, wait_base):
        with self._lock:
            self._wait_base = wait_base

    def _apply(self, pipe, sharded, workers: int | None = None,
               depth: int | None = None, read_ahead: int | None = None,
               reason: str = ""):
        """Actuate knob changes on the live pipeline + record each
        changed knob as a decision.  No controller lock is held while
        touching pipeline locks (lock-order hygiene)."""
        with self._lock:
            cur_w, cur_d, cur_a = self.workers, self.depth, self.read_ahead
        if workers is not None and cur_w is not None \
                and workers != cur_w:
            with self._lock:
                self.workers = int(workers)
            pipe.resize(workers=int(workers))
            self._record_decision("workers", cur_w, int(workers), reason)
            self.metrics.workers.set(int(workers))
        if depth is not None and cur_d is not None and depth != cur_d:
            with self._lock:
                self.depth = int(depth)
            pipe.resize(depth=int(depth))
            self._record_decision("depth", cur_d, int(depth), reason)
            self.metrics.depth.set(int(depth))
        if read_ahead is not None and read_ahead != cur_a:
            with self._lock:
                self.read_ahead = int(read_ahead)
            if sharded is not None:
                sharded.set_read_ahead_count(int(read_ahead))
            self._record_decision("read_ahead", cur_a, int(read_ahead),
                                  reason)
            self.metrics.read_ahead.set(int(read_ahead))

    def _record_decision(self, knob: str, old, new, reason: str):
        record = {"ts": time.time(), "knob": knob, "old": old,
                  "new": new, "reason": reason}
        with self._lock:
            self._decisions.append(dict(record))
        self.metrics.decisions.labels(knob=knob, reason=reason).inc()
        get_flight_recorder().record(
            "autotune", knob=knob, old=old, new=new, reason=reason)
        _append_tune_log({**record, "type": "decision",
                          "pid": os.getpid()})

    # ------------------------------------------------------------------
    # fused-dispatch K (driven inline by the estimator loop)
    # ------------------------------------------------------------------
    def current_k(self) -> int:
        """The K the NEXT chunk should be built with (read by the feeder
        thread at chunk boundaries; plain int read, no lock needed)."""
        return self._k

    def observe_dispatch(self, nk: int, step_s: float) -> None:
        """One measured dispatch: ``nk`` fused inner steps took
        ``step_s`` wall seconds (full loop iteration — the quantity K
        amortizes).  Drives the hill-climb over :attr:`k_candidates`:
        measure ``k_samples`` dispatches at the current K (after
        ``k_warm_skip`` warm dispatches paying the new program's
        compile), then either probe the next candidate up — while the
        current K is still the best seen — or settle on the smallest K
        within ``k_margin`` of the best per-step time.

        With an oracle attached, the FIRST observed dispatch (the
        compiled program's features now exist) consults the prior
        instead: jump to the predicted K and validate only its ±1
        ladder neighbors, with the tighter ``k_prior_*`` window."""
        self._maybe_consult_prior()
        decision = None
        settled = None
        with self._lock:
            self.dispatches_observed += 1
            if self._k_settled or nk != self._k:
                return  # settled, or a stale chunk from before a switch
            self.tuning_dispatches += 1
            k = self._k
            prior_mode = self._k_validate is not None
            warm = self.k_prior_warm_skip if prior_mode \
                else self.k_warm_skip
            if self._k_skip.get(k, 0) < warm:
                self._k_skip[k] = self._k_skip.get(k, 0) + 1
                return
            times = self._k_times.setdefault(k, [])
            times.append(step_s / max(nk, 1))
            if len(times) < (self.k_prior_samples if prior_mode
                             else self.k_samples):
                return
            # mean over the window = window wall time / steps = inverse
            # THROUGHPUT, the quantity being tuned.  Neither min nor
            # median would do: dispatch is async, so the first
            # iterations after a K switch measure only host dispatch
            # cost while the device queue fills (runahead) — k_warm_skip
            # absorbs that fill (and the new program's compile), and the
            # remaining contiguous window averages to the true rate.
            self._k_cost[k] = sum(times) / len(times)
            decision = self._advance_k_locked(k)
            if self._k_settled:
                settled = {
                    "k": self._k,
                    "cost": self._k_cost.get(self._k),
                    "costs": {str(c): round(v, 9) for c, v
                              in sorted(self._k_cost.items())},
                    "label": self._feature_label,
                    "dispatch": self.k_settle_dispatch,
                }
        if decision is not None:
            old, new, reason = decision
            self._record_decision("k", old, new, reason)
            self.metrics.k.set(new)
        if settled is not None:
            self._publish_settle(settled)

    def _advance_k_locked(self, k: int):
        """Next hill-climb move; called with the lock held, returns the
        (old, new, reason) decision or None when K is unchanged."""
        costs = self._k_cost
        best_cost = min(costs.values())
        # smallest candidate within margin of the best: ties go to the
        # smaller K (finer checkpoint/validation cadence for free)
        best_k = min(c for c, m in costs.items()
                     if m <= best_cost * (1.0 + self.k_margin))
        if self._k_validate is not None:
            # oracle-prior mode: walk the fixed validation list (the
            # predicted K and its ladder neighbors), then settle on the
            # best measured — no probing beyond it.  Within the margin
            # the measurements cannot distinguish candidates, so the
            # tie goes to the PREDICTED K (the analytic ranking breaks
            # the tie), not the smallest — a noisy 2-sample validation
            # window must not drag the settle off a sound prediction.
            # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
            self._k_validate = [c for c in self._k_validate if c != k]
            if self._k_validate:
                # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
                self._k = self._k_validate[0]
                return (k, self._k, "validate_neighbor")
            within = {c for c, m in costs.items()
                      if m <= best_cost * (1.0 + self.k_margin)}
            if self._k_prior_hint in within:
                best_k = self._k_prior_hint
            # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
            self._k = best_k
            # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
            self._k_settled = True
            # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
            self.k_settle_dispatch = self.tuning_dispatches
            return (k, best_k, "settled") if best_k != k else None
        i = self.k_candidates.index(k)
        if k == best_k and i + 1 < len(self.k_candidates):
            # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
            self._k = self.k_candidates[i + 1]
            return (k, self._k, "probe_up")
        # current K stopped improving (or the ladder is exhausted):
        # settle on the best measured
        # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
        self._k = best_k
        # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
        self._k_settled = True
        # zoolint: disable=guarded-by -- _locked suffix: observe_dispatch holds _lock across this call
        self.k_settle_dispatch = self.tuning_dispatches
        return (k, best_k, "settled") if best_k != k else None

    def _maybe_consult_prior(self) -> None:
        """One-shot oracle consult at the first observed dispatch: the
        K=1 program has compiled by then, so its HLO features exist in
        the analysis tier's last-report cache.  On a usable prediction,
        jump to the predicted K and arm the ±1 validation list; on any
        failure (no label, nothing compiled, oracle error) the blind
        hill-climb proceeds untouched."""
        oracle = self.oracle
        if oracle is None:
            return
        with self._lock:
            if self._prior_consulted or self._k_settled:
                return
            self._prior_consulted = True
            label = self._feature_label
        features = None
        if label:
            try:
                from analytics_zoo_tpu.analysis.hlo import last_features

                features = last_features(label)
            except Exception:
                features = None
        if features is None:
            return
        try:
            k_hat = int(oracle.predict_k(features, self.k_candidates))
            i = self.k_candidates.index(k_hat)
        except Exception:
            return  # a broken prior must never take the loop down
        neighbors = [self.k_candidates[j] for j in (i - 1, i + 1)
                     if 0 <= j < len(self.k_candidates)]
        with self._lock:
            if self._k_settled:
                return
            old = self._k
            self._k_validate = [k_hat] + neighbors
            self._k_prior_hint = k_hat
            self._k = k_hat
        if k_hat != old:
            self._record_decision("k", old, k_hat, "oracle_prior")
            self.metrics.k.set(k_hat)

    def _publish_settle(self, settled: dict) -> None:
        """Outside-lock settle fan-out: the persistent tune-log record
        (the oracle's cross-restart training join: label + the full
        measured cost curve) and the prediction→outcome closure."""
        _append_tune_log({
            "ts": time.time(), "type": "settle", "pid": os.getpid(),
            "label": settled["label"], "k": settled["k"],
            "k_cost_per_step_s": settled["costs"],
            "dispatches": settled["dispatch"],
        })
        if self.oracle is not None and settled["cost"]:
            try:
                self.oracle.record_outcome(
                    f"k={settled['k']}", 1.0 / settled["cost"],
                    consumer="autotune_k")
            except Exception:
                pass  # outcome bookkeeping must never take the loop down

    @property
    def k_settled(self) -> bool:
        return self._k_settled

    # ------------------------------------------------------------------
    # introspection (/varz, metrics_dump, benches)
    # ------------------------------------------------------------------
    def decision_log(self) -> list[dict]:
        with self._lock:
            return list(self._decisions)

    def current(self) -> dict:
        with self._lock:
            return {
                "workers": self.workers,
                "depth": self.depth,
                "read_ahead": self.read_ahead,
                "k": self._k,
                "k_settled": self._k_settled,
                "k_cost_per_step_s": {
                    str(kk): round(v, 6)
                    for kk, v in sorted(self._k_cost.items())},
                "ram_budget_bytes": self.ram_budget,
                "dispatches_observed": self.dispatches_observed,
                "tuning_dispatches": self.tuning_dispatches,
                "k_settle_dispatch": self.k_settle_dispatch,
            }

    def to_doc(self) -> dict:
        return {"current": self.current(), "decisions": self.decision_log()}
