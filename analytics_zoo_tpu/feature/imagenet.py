"""ImageNet-directory ingestion shared by the training examples/bench.

Accepts either TFRecord shards (``*.tfrecord`` or ``train-*-of-*``, the
standard ImageNet layout: ``image/encoded`` JPEG + 1-based
``image/class/label``) or ``.npz`` shards (``x`` uint8 HWC images + ``y``
labels).  Reference role: the ImageNet loaders of
examples/inception/ImageNet2012.scala and the resnet example's
SSD-style shard reading.
"""

from __future__ import annotations

import glob
import os

from analytics_zoo_tpu.feature.dataset import FeatureSet


def imagenet_feature_set(data_dir: str,
                         image_size: int = 224) -> FeatureSet:
    """FeatureSet over an ImageNet-layout directory (uint8 images out;
    normalization belongs on device via ``transform_on_device``)."""
    tfrec = sorted(glob.glob(os.path.join(data_dir, "*.tfrecord"))
                   + glob.glob(os.path.join(data_dir, "train-*-of-*")))
    if tfrec:
        from analytics_zoo_tpu.feature.tfrecord import (
            imagenet_example_parser,
        )

        return FeatureSet.from_tfrecord(
            tfrec, imagenet_example_parser(image_size=image_size,
                                           label_offset=-1))
    npz = sorted(glob.glob(os.path.join(data_dir, "*.npz")))
    if not npz:
        raise FileNotFoundError(
            f"{data_dir}: no TFRecord (*.tfrecord / train-*-of-*) or .npz "
            "shards found")
    return FeatureSet.from_shards(npz)
