"""Parallel host data plane — multi-worker prefetch pipeline for FeatureSet.

The reference hid data-loading latency behind Spark's distributed readers
(DiskFeatureSet's resident-slice design, FeatureSet.scala:332-409); this
rebuild's generators are single-threaded, so the only latency hiding left
was the estimator's double-buffered infeed slot.  tf.data (PAPERS.md,
arxiv 2101.12127) showed that parallel extract/transform with a bounded
prefetch buffer and ORDERED delivery is what turns an input pipeline from
the bottleneck into a non-factor — this module is that shape for
FeatureSet:

- :class:`PrefetchPipeline`: a producer thread walks the source iterator
  (shard loading, index selection, raw batch assembly) and hands the
  expensive per-batch work (host ``Preprocessing`` transforms, decode) to
  a thread pool; a bounded queue of IN-ORDER futures delivers batches to
  the consumer.  Futures are enqueued in source order, so worker
  completion order can never reorder the stream: same ``seed``/``epoch``
  ⇒ byte-identical batch stream vs. the serial path.
- Shard read-ahead: while a :class:`ShardedFeatureSet` slice is being
  consumed, the NEXT shard's ``loader(path)`` runs on the pool, so
  advancing the resident slice no longer stalls the feeder cold.
- Exception propagation: a worker/source error surfaces to the consumer
  at the stream position it occurred, then the pool and producer shut
  down cleanly (no orphaned threads, no wedged queue).
- Telemetry: ``zoo_data_prefetch_*`` (queue occupancy gauge,
  producer-stall / consumer-wait histograms, delivered-batch counter)
  plus an ``infeed``-style ``data_prefetch`` health heartbeat the
  producer beats per batch — a wedged input pipeline flips /healthz.

Thread workers scale work that releases the GIL (file IO, numpy decode,
cv2); pure-python transforms still win read-ahead — the producer runs off
the consumer thread — but not parallel speedup.

Determinism contract: the stream is byte-identical to the serial path
provided the transforms themselves are deterministic per record (seeded
per-(record, epoch) RNG, as the in-repo image ROI transforms are).  A
transform drawing from a process-global RNG would see a different draw
ORDER under concurrency — that is a property of the transform, not of
the pipeline's delivery order, which is always the serial order.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Iterable

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.dataset import (
    FeatureSet,
    ShardedFeatureSet,
    TransformedFeatureSet,
    _host_nbytes,
    _preprocess_batch,
)
from analytics_zoo_tpu.metrics import DataPipelineMetrics, get_health

__all__ = ["PrefetchPipeline", "PrefetchFeatureSet", "FusedPreprocessing",
           "worth_prefetching"]


class FusedPreprocessing(Preprocessing):
    """N stacked transforms fused into ONE per-record callable (the
    map-fusion stage), with each intermediate materialized exactly the
    way the serial nested path hands it to the next stage.

    Serially, stage i's per-record outputs pass through ``np.stack``
    (batch re-assembly) before stage i+1 re-extracts its row: the next
    stage always receives an ``ndarray`` row (or a tuple of rows for
    multi-input batches), never stage i's raw Python return.  Plain
    function composition would leak raw returns (a list, a scalar)
    straight into stage i+1 — crashing or producing different bytes
    only under prefetch.  ``np.asarray`` per record reproduces the
    serial materialization for the deterministic same-dtype-per-record
    transforms the byte-identity contract covers, while skipping the
    N-1 full batch stack/unstack passes fusion exists to remove."""

    def __init__(self, stages):
        self.stages = list(stages)

    @staticmethod
    def _materialize(record):
        if isinstance(record, tuple):
            return tuple(np.asarray(a) for a in record)
        return np.asarray(record)

    def transform(self, record):
        last = len(self.stages) - 1
        for i, stage in enumerate(self.stages):
            record = stage(record)
            if i != last:
                record = self._materialize(record)
        return record


def worth_prefetching(fs) -> bool:
    """True when the prefetch plane has host work to hide: a
    ``Preprocessing`` chain (the pooled map stage), a sharded/disk base
    (shard loads + read-ahead), or a PMEM-spilled array set (page-cache
    reads).  A resident DRAM ``ArrayFeatureSet`` with no transforms has
    nothing to move off-thread — wrapping it only adds queue handoffs
    per batch, which is why the autotuner (feature/autotune.py) consults
    this before injecting the pipeline.  Unknown FeatureSet types return
    True (their ``batches()`` cost is unknowable; read-ahead is the safe
    default)."""
    from analytics_zoo_tpu.feature.dataset import ArrayFeatureSet

    inner = fs
    while isinstance(inner, (TransformedFeatureSet, PrefetchFeatureSet)):
        if isinstance(inner, TransformedFeatureSet):
            return True
        inner = inner.base
    if isinstance(inner, ShardedFeatureSet):
        return True
    if isinstance(inner, ArrayFeatureSet):
        return getattr(inner, "_spool", None) is not None
    return True

# queue item kinds: a raw value, an in-flight future, end-of-stream
_VALUE, _FUTURE, _END = 0, 1, 2


class _ResizableQueue:
    """Bounded FIFO whose CAPACITY can change while producers and
    consumers are blocked on it (the autotune depth knob,
    feature/autotune.py).

    ``queue.Queue`` fixes ``maxsize`` at construction; resizing the
    prefetch window online must not drain or replace the queue — the
    items in it are the in-order stream, and delivery order is the
    byte-identity contract.  One deque + one Condition: :meth:`resize`
    only moves the capacity watermark and wakes waiters, so a grow
    unblocks a stalled producer immediately and a shrink simply stops
    admitting until the consumer drains below the new bound (queued
    items are never dropped).  API mirrors the ``queue.Queue`` subset
    the pipeline uses (timeout put/get raising Full/Empty).
    """

    def __init__(self, capacity: int):
        self._cond = threading.Condition()
        self._items: collections.deque = collections.deque()  # guarded-by: _cond
        self._capacity = int(capacity)  # guarded-by: _cond

    def put(self, item, timeout: float | None = None):
        with self._cond:
            if len(self._items) >= self._capacity:
                self._cond.wait(timeout)
                if len(self._items) >= self._capacity:
                    raise queue.Full
            self._items.append(item)
            self._cond.notify_all()

    def get(self, timeout: float | None = None):
        with self._cond:
            if not self._items:
                self._cond.wait(timeout)
                if not self._items:
                    raise queue.Empty
            item = self._items.popleft()
            self._cond.notify_all()
            return item

    def get_nowait(self):
        with self._cond:
            if not self._items:
                raise queue.Empty
            item = self._items.popleft()
            self._cond.notify_all()
            return item

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)

    @property
    def capacity(self) -> int:
        return self._capacity

    def resize(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        with self._cond:
            self._capacity = int(capacity)
            self._cond.notify_all()


class _WorkerPool:
    """Thread pool whose worker count can grow AND shrink online (the
    autotune workers knob) — ``ThreadPoolExecutor`` only grows.

    ``submit`` returns a real :class:`concurrent.futures.Future`, so the
    pipeline's in-order future queue (and shard read-ahead, which rides
    the same pool) is unchanged.  Grow spawns threads immediately;
    shrink is lazy — surplus workers exit between tasks when they notice
    the lower target, so no in-flight transform is interrupted and
    delivery order is untouched.  ``shutdown`` stops dispatch; queued
    futures are left cancellable (the pipeline cancels them on close).
    """

    def __init__(self, workers: int, thread_name_prefix: str = "zoo-prefetch"):
        self._prefix = thread_name_prefix
        self._cond = threading.Condition()
        self._tasks: collections.deque = collections.deque()  # guarded-by: _cond
        self._target = int(workers)  # guarded-by: _cond
        self._live = 0  # guarded-by: _cond
        self._seq = 0  # guarded-by: _cond
        self._shutdown = False  # guarded-by: _cond
        self._spawn()

    def _spawn(self):
        new = []
        with self._cond:
            while not self._shutdown and self._live < self._target:
                self._live += 1
                self._seq += 1
                new.append(threading.Thread(
                    target=self._run, daemon=True,
                    name=f"{self._prefix}-{self._seq}"))
        for t in new:
            t.start()

    def _run(self):
        while True:
            with self._cond:
                if self._shutdown or self._live > self._target:
                    self._live -= 1
                    return
                if not self._tasks:
                    self._cond.wait(0.1)  # re-check shutdown/shrink
                    continue
                fut, fn, args = self._tasks.popleft()
            if not fut.set_running_or_notify_cancel():
                continue  # cancelled while queued (pipeline close)
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # delivered via future.result()
                fut.set_exception(e)

    def submit(self, fn, /, *args) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._shutdown:
                # the ThreadPoolExecutor contract read-ahead relies on;
                # checked ATOMICALLY with the enqueue, so no task can
                # slip in behind shutdown's drain and pend forever
                raise RuntimeError("cannot submit after shutdown")
            self._tasks.append((fut, fn, args))
            self._cond.notify()
        return fut

    @property
    def max_workers(self) -> int:
        return self._target

    def resize(self, workers: int):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        with self._cond:
            self._target = int(workers)
            self._cond.notify_all()
        self._spawn()

    def shutdown(self, wait: bool = False):
        with self._cond:
            self._shutdown = True
            pending = list(self._tasks)
            self._tasks.clear()
            self._cond.notify_all()
        for fut, _, _ in pending:
            # never-started tasks resolve as CANCELLED instead of
            # pending forever: a consumer concurrently blocked in
            # future.result() gets CancelledError, not a hang (the
            # ThreadPoolExecutor path ran queued work; we cancel it)
            fut.cancel()
        if wait:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with self._cond:
                    if self._live == 0:
                        return
                time.sleep(0.01)


# host bytes of one delivered batch (0 for non-dict payloads) — the
# SAME accounting ShardedFeatureSet uses for shard sizes, so the
# autotune RAM estimate never diverges between the two
_batch_nbytes = _host_nbytes


class PrefetchPipeline:
    """Thread-pool-backed, bounded-queue, ORDER-PRESERVING prefetcher.

    ``source`` is iterated by a dedicated producer thread; each item is
    either forwarded as-is (``map_fn=None`` — pure read-ahead) or
    submitted to a ``workers``-wide pool as ``map_fn(item)``.  The bounded
    queue (``depth``) holds futures in source order, so the consumer sees
    the exact serial stream while up to ``depth`` items are in flight and
    up to ``workers`` transforms run concurrently.

    Iterate the pipeline to consume; call :meth:`close` (or use it as a
    context manager) to shut down early.  A source or worker exception is
    re-raised to the consumer at its stream position.
    """

    def __init__(self, source: Iterable, map_fn: Callable | None = None,
                 workers: int = 2, depth: int = 4,
                 metrics: DataPipelineMetrics | None = None,
                 health_component: str = "data_prefetch",
                 stale_after: float = 60.0, start: bool = True):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = int(workers)
        self.depth = int(depth)
        self._source = iter(source)
        self._map_fn = map_fn
        self._metrics = metrics if metrics is not None \
            else DataPipelineMetrics()
        self._metrics.workers.set(self.workers)
        self._metrics.depth_limit.set(self.depth)
        self._q = _ResizableQueue(self.depth)
        self._stop = threading.Event()
        self._pool = _WorkerPool(self.workers)
        self._hc = health_component
        self._stale_after = stale_after
        self._producer = threading.Thread(
            target=self._produce, daemon=True, name="zoo-prefetch-producer")
        if start:
            self._producer.start()

    def start(self) -> "PrefetchPipeline":
        """Start the producer (no-op if already running).  Construct
        with ``start=False`` when source-side state must attach to
        :attr:`pool` first — e.g. shard read-ahead: starting the
        producer before ``set_read_ahead(pipe.pool)`` would let the
        first loads race the attachment and fall back to synchronous
        loading on the producer thread."""
        if not self._producer.is_alive():
            try:
                self._producer.start()
            except RuntimeError:
                pass  # already started and finished: nothing to do
        return self

    # ------------------------------------------------------------------
    @property
    def pool(self) -> _WorkerPool:
        """The worker pool — ShardedFeatureSet read-ahead rides it too."""
        return self._pool

    @property
    def metrics(self) -> DataPipelineMetrics:
        """This pipeline's telemetry — the autotune controller reads its
        consumer-wait/producer-stall deltas to steer :meth:`resize`."""
        return self._metrics

    def resize(self, workers: int | None = None, depth: int | None = None):
        """Grow/shrink the worker pool and/or the bounded queue ONLINE —
        no drain, no re-creation, in-order delivery untouched (the queue
        of in-flight futures IS the stream; only watermarks move).

        The autotune controller's actuator (feature/autotune.py); also
        usable directly.  A depth shrink never drops queued batches — it
        stops admitting until the consumer drains below the new bound; a
        worker shrink lets surplus threads finish their current transform
        and exit between tasks.
        """
        if workers is not None:
            if workers < 1:
                raise ValueError(f"workers must be >= 1, got {workers}")
            self.workers = int(workers)
            self._pool.resize(self.workers)
            self._metrics.workers.set(self.workers)
        if depth is not None:
            if depth < 1:
                raise ValueError(f"depth must be >= 1, got {depth}")
            self.depth = int(depth)
            self._q.resize(self.depth)
            self._metrics.depth_limit.set(self.depth)

    # zoolint: hot-path
    def _put(self, item) -> bool:
        """Bounded put that respects close(); False when shut down.

        The time blocked on a full queue is the producer-stall histogram:
        a fat stall p99 means the consumer (device) is the bottleneck and
        the pipeline is keeping up — the healthy direction."""
        t0 = time.perf_counter()
        health = get_health()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                self._metrics.producer_stall.observe(
                    time.perf_counter() - t0)
                self._metrics.queue_depth.set(self._q.qsize())
                return True
            except queue.Full:
                # still alive, just ahead of the consumer — keep beating
                health.heartbeat(self._hc)
        return False

    # zoolint: hot-path
    def _produce(self):
        health = get_health()
        health.register(self._hc, stale_after=self._stale_after)
        err: BaseException | None = None
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                health.heartbeat(self._hc)
                if self._map_fn is not None:
                    if not self._put((_FUTURE,
                                      self._pool.submit(self._map_fn, item))):
                        return
                elif not self._put((_VALUE, item)):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err = e
        finally:
            # unregister BEFORE the final put, on this thread: a pipeline
            # that finished early (everything buffered) must not read as
            # stale while the consumer drains, and no late beat can
            # resurrect the component (the _DeviceFeeder on_exit rule)
            health.unregister(self._hc)
            self._put((_END, err))

    # zoolint: hot-path
    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            while True:
                try:
                    kind, payload = self._q.get(timeout=0.1)
                    break
                except queue.Empty:
                    if self._stop.is_set() \
                            and not self._producer.is_alive():
                        return  # closed under us; producer already gone
            if kind == _END:
                self._metrics.consumer_wait.observe(
                    time.perf_counter() - t0)
                self._metrics.queue_depth.set(self._q.qsize())
                if payload is not None:
                    self._metrics.errors.inc()
                    raise payload
                return
            if kind == _FUTURE:
                try:
                    payload = payload.result()
                except BaseException:
                    self._metrics.errors.inc()
                    self.close()
                    raise
            # consumer_wait covers queue get AND the future's remaining
            # transform time: futures enqueue the moment the source
            # yields, so a transform-bound pipeline starves the consumer
            # inside result(), not get() — the autotune controller
            # steers on this histogram, so it must see BOTH.
            self._metrics.consumer_wait.observe(time.perf_counter() - t0)
            self._metrics.queue_depth.set(self._q.qsize())
            self._metrics.batches.inc()
            if self._metrics.enabled:
                # last-delivered batch bytes: the autotune RAM-budget
                # estimator's input (resident ≈ bytes x (depth+workers))
                self._metrics.batch_bytes.set(_batch_nbytes(payload))
            yield payload

    def close(self):
        """Stop the producer, cancel queued work, release the pool."""
        self._stop.set()
        # drain: unblocks a producer stuck on a full queue and drops
        # not-yet-started futures before the pool shutdown
        while True:
            try:
                kind, payload = self._q.get_nowait()
            except queue.Empty:
                break
            if kind == _FUTURE:
                payload.cancel()
        self._producer.join(timeout=5.0)
        self._pool.shutdown(wait=False)
        self._metrics.queue_depth.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrefetchFeatureSet(FeatureSet):
    """``FeatureSet.prefetch(depth, workers)`` — same stream, off-thread.

    ``batches(...)`` yields the byte-identical stream of the wrapped
    FeatureSet, produced through a :class:`PrefetchPipeline`:

    - a :class:`TransformedFeatureSet` base is split at the transform
      boundary — raw batch assembly runs on the producer thread, the
      per-record ``Preprocessing`` runs batch-at-a-time on the pool
      (the parallel-map stage, where ``workers`` actually buys speedup);
    - a :class:`ShardedFeatureSet` base (directly or under transforms)
      additionally read-ahead-loads shard k+1 on the pool while shard k's
      batches are being consumed, so the resident-slice advance costs no
      feeder stall.

    Composes with the estimator's double-buffered device infeed
    untouched: the feeder simply consumes this iterator instead of the
    serial generator.
    """

    def __init__(self, base: FeatureSet, depth: int = 4, workers: int = 2,
                 metrics: DataPipelineMetrics | None = None,
                 controller=None):
        self.base = base
        self.depth = int(depth)
        self.workers = int(workers)
        self._metrics = metrics
        # AutotuneController (feature/autotune.py): when attached, each
        # epoch's pipeline starts at the controller's CURRENT tuned
        # (workers, depth) — convergence accumulates across the
        # per-batches() pipeline lifetimes — and the controller gets the
        # live pipeline handle to resize mid-epoch.
        self._controller = controller

    # -- delegation (the TransformedFeatureSet pattern) -----------------
    @property
    def device_transform(self):
        return self.base.device_transform

    @device_transform.setter
    def device_transform(self, fn):
        self.base.device_transform = fn

    @property
    def num_samples(self) -> int:
        return self.base.num_samples

    def transform(self, preprocessing) -> "PrefetchFeatureSet":
        """Keep the prefetch stage outermost so new transforms join the
        pooled map stage instead of running on the consumer thread."""
        return PrefetchFeatureSet(self.base.transform(preprocessing),
                                  self.depth, self.workers, self._metrics,
                                  controller=self._controller)

    def prefetch(self, depth: int = 4, workers: int = 2) \
            -> "PrefetchFeatureSet":
        return PrefetchFeatureSet(self.base, depth, workers, self._metrics,
                                  controller=self._controller)

    # ------------------------------------------------------------------
    def batches(self, *args, **kwargs):
        # Split at the transform boundary: everything below the
        # (possibly nested) TransformedFeatureSet wrappers is the source
        # walked serially by the producer; the collected preprocessing
        # chain is the pooled map stage.  Delivery order is source order,
        # so the emitted stream equals base.batches exactly.
        chain = []
        inner = self.base
        while isinstance(inner, TransformedFeatureSet):
            chain.append(inner.preprocessing)
            inner = inner.base
        chain.reverse()  # innermost transform applies first

        map_fn = None
        if chain:
            # Map-fusion: N stacked transforms fuse into ONE per-record
            # callable, so the pool pays one unstack/apply/restack pass
            # per batch instead of N (FusedPreprocessing materializes
            # each intermediate the way the serial np.stack boundary
            # does, keeping the stream byte-identical).
            fused = chain[0] if len(chain) == 1 \
                else FusedPreprocessing(chain)

            def map_fn(batch, _pre=fused):
                return _preprocess_batch(_pre, batch)

        ctrl = self._controller
        workers, depth, metrics = self.workers, self.depth, self._metrics
        if ctrl is not None:
            workers, depth = ctrl.pipeline_config(workers, depth)
            if metrics is None:
                metrics = ctrl.data_metrics
        sharded = inner if isinstance(inner, ShardedFeatureSet) else None
        # start=False: read-ahead must attach to the pool BEFORE the
        # producer walks the first shards, or the attachment races the
        # early loads (observed as synchronous producer-thread loads)
        pipe = PrefetchPipeline(
            inner.batches(*args, **kwargs), map_fn=map_fn,
            workers=workers, depth=depth, metrics=metrics,
            start=False)
        if sharded is not None:
            sharded.set_read_ahead(pipe.pool)
        if ctrl is not None:
            ctrl.attach_pipeline(pipe, sharded=sharded)
        pipe.start()
        try:
            yield from pipe
        finally:
            if ctrl is not None:
                ctrl.detach_pipeline(pipe)
            if sharded is not None:
                sharded.set_read_ahead(None)
            pipe.close()
