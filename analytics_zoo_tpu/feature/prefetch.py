"""Parallel host data plane — multi-worker prefetch pipeline for FeatureSet.

The reference hid data-loading latency behind Spark's distributed readers
(DiskFeatureSet's resident-slice design, FeatureSet.scala:332-409); this
rebuild's generators are single-threaded, so the only latency hiding left
was the estimator's double-buffered infeed slot.  tf.data (PAPERS.md,
arxiv 2101.12127) showed that parallel extract/transform with a bounded
prefetch buffer and ORDERED delivery is what turns an input pipeline from
the bottleneck into a non-factor — this module is that shape for
FeatureSet:

- :class:`PrefetchPipeline`: a producer thread walks the source iterator
  (shard loading, index selection, raw batch assembly) and hands the
  expensive per-batch work (host ``Preprocessing`` transforms, decode) to
  a thread pool; a bounded queue of IN-ORDER futures delivers batches to
  the consumer.  Futures are enqueued in source order, so worker
  completion order can never reorder the stream: same ``seed``/``epoch``
  ⇒ byte-identical batch stream vs. the serial path.
- Shard read-ahead: while a :class:`ShardedFeatureSet` slice is being
  consumed, the NEXT shard's ``loader(path)`` runs on the pool, so
  advancing the resident slice no longer stalls the feeder cold.
- Exception propagation: a worker/source error surfaces to the consumer
  at the stream position it occurred, then the pool and producer shut
  down cleanly (no orphaned threads, no wedged queue).
- Telemetry: ``zoo_data_prefetch_*`` (queue occupancy gauge,
  producer-stall / consumer-wait histograms, delivered-batch counter)
  plus an ``infeed``-style ``data_prefetch`` health heartbeat the
  producer beats per batch — a wedged input pipeline flips /healthz.

Thread workers scale work that releases the GIL (file IO, numpy decode,
cv2); pure-python transforms still win read-ahead — the producer runs off
the consumer thread — but not parallel speedup.

Determinism contract: the stream is byte-identical to the serial path
provided the transforms themselves are deterministic per record (seeded
per-(record, epoch) RNG, as the in-repo image ROI transforms are).  A
transform drawing from a process-global RNG would see a different draw
ORDER under concurrency — that is a property of the transform, not of
the pipeline's delivery order, which is always the serial order.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable

from analytics_zoo_tpu.feature.dataset import (
    FeatureSet,
    ShardedFeatureSet,
    TransformedFeatureSet,
    _preprocess_batch,
)
from analytics_zoo_tpu.metrics import DataPipelineMetrics, get_health

__all__ = ["PrefetchPipeline", "PrefetchFeatureSet"]

# queue item kinds: a raw value, an in-flight future, end-of-stream
_VALUE, _FUTURE, _END = 0, 1, 2


class PrefetchPipeline:
    """Thread-pool-backed, bounded-queue, ORDER-PRESERVING prefetcher.

    ``source`` is iterated by a dedicated producer thread; each item is
    either forwarded as-is (``map_fn=None`` — pure read-ahead) or
    submitted to a ``workers``-wide pool as ``map_fn(item)``.  The bounded
    queue (``depth``) holds futures in source order, so the consumer sees
    the exact serial stream while up to ``depth`` items are in flight and
    up to ``workers`` transforms run concurrently.

    Iterate the pipeline to consume; call :meth:`close` (or use it as a
    context manager) to shut down early.  A source or worker exception is
    re-raised to the consumer at its stream position.
    """

    def __init__(self, source: Iterable, map_fn: Callable | None = None,
                 workers: int = 2, depth: int = 4,
                 metrics: DataPipelineMetrics | None = None,
                 health_component: str = "data_prefetch",
                 stale_after: float = 60.0, start: bool = True):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = int(workers)
        self.depth = int(depth)
        self._source = iter(source)
        self._map_fn = map_fn
        self._metrics = metrics if metrics is not None \
            else DataPipelineMetrics()
        self._metrics.workers.set(self.workers)
        self._metrics.depth_limit.set(self.depth)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="zoo-prefetch")
        self._hc = health_component
        self._stale_after = stale_after
        self._producer = threading.Thread(
            target=self._produce, daemon=True, name="zoo-prefetch-producer")
        if start:
            self._producer.start()

    def start(self) -> "PrefetchPipeline":
        """Start the producer (no-op if already running).  Construct
        with ``start=False`` when source-side state must attach to
        :attr:`pool` first — e.g. shard read-ahead: starting the
        producer before ``set_read_ahead(pipe.pool)`` would let the
        first loads race the attachment and fall back to synchronous
        loading on the producer thread."""
        if not self._producer.is_alive():
            try:
                self._producer.start()
            except RuntimeError:
                pass  # already started and finished: nothing to do
        return self

    # ------------------------------------------------------------------
    @property
    def pool(self) -> ThreadPoolExecutor:
        """The worker pool — ShardedFeatureSet read-ahead rides it too."""
        return self._pool

    # zoolint: hot-path
    def _put(self, item) -> bool:
        """Bounded put that respects close(); False when shut down.

        The time blocked on a full queue is the producer-stall histogram:
        a fat stall p99 means the consumer (device) is the bottleneck and
        the pipeline is keeping up — the healthy direction."""
        t0 = time.perf_counter()
        health = get_health()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                self._metrics.producer_stall.observe(
                    time.perf_counter() - t0)
                self._metrics.queue_depth.set(self._q.qsize())
                return True
            except queue.Full:
                # still alive, just ahead of the consumer — keep beating
                health.heartbeat(self._hc)
        return False

    # zoolint: hot-path
    def _produce(self):
        health = get_health()
        health.register(self._hc, stale_after=self._stale_after)
        err: BaseException | None = None
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                health.heartbeat(self._hc)
                if self._map_fn is not None:
                    if not self._put((_FUTURE,
                                      self._pool.submit(self._map_fn, item))):
                        return
                elif not self._put((_VALUE, item)):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err = e
        finally:
            # unregister BEFORE the final put, on this thread: a pipeline
            # that finished early (everything buffered) must not read as
            # stale while the consumer drains, and no late beat can
            # resurrect the component (the _DeviceFeeder on_exit rule)
            health.unregister(self._hc)
            self._put((_END, err))

    # zoolint: hot-path
    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            while True:
                try:
                    kind, payload = self._q.get(timeout=0.1)
                    break
                except queue.Empty:
                    if self._stop.is_set() \
                            and not self._producer.is_alive():
                        return  # closed under us; producer already gone
            self._metrics.consumer_wait.observe(time.perf_counter() - t0)
            self._metrics.queue_depth.set(self._q.qsize())
            if kind == _END:
                if payload is not None:
                    self._metrics.errors.inc()
                    raise payload
                return
            if kind == _FUTURE:
                try:
                    payload = payload.result()
                except BaseException:
                    self._metrics.errors.inc()
                    self.close()
                    raise
            self._metrics.batches.inc()
            yield payload

    def close(self):
        """Stop the producer, cancel queued work, release the pool."""
        self._stop.set()
        # drain: unblocks a producer stuck on a full queue and drops
        # not-yet-started futures before the pool shutdown
        while True:
            try:
                kind, payload = self._q.get_nowait()
            except queue.Empty:
                break
            if kind == _FUTURE:
                payload.cancel()
        self._producer.join(timeout=5.0)
        self._pool.shutdown(wait=False)
        self._metrics.queue_depth.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrefetchFeatureSet(FeatureSet):
    """``FeatureSet.prefetch(depth, workers)`` — same stream, off-thread.

    ``batches(...)`` yields the byte-identical stream of the wrapped
    FeatureSet, produced through a :class:`PrefetchPipeline`:

    - a :class:`TransformedFeatureSet` base is split at the transform
      boundary — raw batch assembly runs on the producer thread, the
      per-record ``Preprocessing`` runs batch-at-a-time on the pool
      (the parallel-map stage, where ``workers`` actually buys speedup);
    - a :class:`ShardedFeatureSet` base (directly or under transforms)
      additionally read-ahead-loads shard k+1 on the pool while shard k's
      batches are being consumed, so the resident-slice advance costs no
      feeder stall.

    Composes with the estimator's double-buffered device infeed
    untouched: the feeder simply consumes this iterator instead of the
    serial generator.
    """

    def __init__(self, base: FeatureSet, depth: int = 4, workers: int = 2,
                 metrics: DataPipelineMetrics | None = None):
        self.base = base
        self.depth = int(depth)
        self.workers = int(workers)
        self._metrics = metrics

    # -- delegation (the TransformedFeatureSet pattern) -----------------
    @property
    def device_transform(self):
        return self.base.device_transform

    @device_transform.setter
    def device_transform(self, fn):
        self.base.device_transform = fn

    @property
    def num_samples(self) -> int:
        return self.base.num_samples

    def transform(self, preprocessing) -> "PrefetchFeatureSet":
        """Keep the prefetch stage outermost so new transforms join the
        pooled map stage instead of running on the consumer thread."""
        return PrefetchFeatureSet(self.base.transform(preprocessing),
                                  self.depth, self.workers, self._metrics)

    def prefetch(self, depth: int = 4, workers: int = 2) \
            -> "PrefetchFeatureSet":
        return PrefetchFeatureSet(self.base, depth, workers, self._metrics)

    # ------------------------------------------------------------------
    def batches(self, *args, **kwargs):
        # Split at the transform boundary: everything below the
        # (possibly nested) TransformedFeatureSet wrappers is the source
        # walked serially by the producer; the collected preprocessing
        # chain is the pooled map stage.  Delivery order is source order,
        # so the emitted stream equals base.batches exactly.
        chain = []
        inner = self.base
        while isinstance(inner, TransformedFeatureSet):
            chain.append(inner.preprocessing)
            inner = inner.base
        chain.reverse()  # innermost transform applies first

        map_fn = None
        if chain:
            def map_fn(batch, _chain=tuple(chain)):
                for pre in _chain:
                    batch = _preprocess_batch(pre, batch)
                return batch

        sharded = inner if isinstance(inner, ShardedFeatureSet) else None
        # start=False: read-ahead must attach to the pool BEFORE the
        # producer walks the first shards, or the attachment races the
        # early loads (observed as synchronous producer-thread loads)
        pipe = PrefetchPipeline(
            inner.batches(*args, **kwargs), map_fn=map_fn,
            workers=self.workers, depth=self.depth, metrics=self._metrics,
            start=False)
        if sharded is not None:
            sharded.set_read_ahead(pipe.pool)
        pipe.start()
        try:
            yield from pipe
        finally:
            if sharded is not None:
                sharded.set_read_ahead(None)
            pipe.close()
