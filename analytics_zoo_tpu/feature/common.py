"""Composable preprocessing — reference ``Preprocessing[A,B]`` with the
``->`` chaining operator (zoo/.../feature/common/Preprocessing.scala;
FeatureSet.scala:82-84 uses it to attach transformers to datasets).

Python can't overload ``->``, so chaining is ``a >> b`` (or
``ChainedPreprocessing([a, b])``).  Transformers are host-side, pure
per-record functions; anything per-batch and numeric should instead be fused
into the jitted step where XLA can overlap it with compute.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class Preprocessing:
    """A per-record transform; subclass and implement ``transform``."""

    def transform(self, record: Any) -> Any:
        raise NotImplementedError

    def __call__(self, record: Any) -> Any:
        return self.transform(record)

    def apply_iter(self, records: Iterable) -> Iterable:
        for r in records:
            yield self.transform(r)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        """``a >> b`` ≡ reference ``a -> b`` (Preprocessing.scala)."""
        return ChainedPreprocessing([self, other])


class ChainedPreprocessing(Preprocessing):
    def __init__(self, stages: list[Preprocessing]):
        flat = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat

    def transform(self, record):
        for s in self.stages:
            record = s.transform(record)
        return record


class FnPreprocessing(Preprocessing):
    def __init__(self, fn: Callable):
        self.fn = fn

    def transform(self, record):
        return self.fn(record)


class FeatureLabelPreprocessing(Preprocessing):
    """Applies separate transforms to (feature, label) pairs — reference
    feature/common FeatureLabelPreprocessing."""

    def __init__(self, feature_transform: Preprocessing,
                 label_transform: Preprocessing | None = None):
        self.feature_transform = feature_transform
        self.label_transform = label_transform

    def transform(self, record):
        x, y = record
        x = self.feature_transform.transform(x)
        if self.label_transform is not None:
            y = self.label_transform.transform(y)
        return x, y
