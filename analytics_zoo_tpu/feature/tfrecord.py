"""TFRecord file reading + tf.train.Example codec — no tensorflow needed.

Reference: ``TFDataset.from_tfrecord_file`` (pyzoo
zoo/pipeline/api/net/tf_dataset.py:456-501 — reads TFRecord bytes via a
Hadoop input format into an RDD) and the byte/feature dataset variants
(:629-713).  Here the TFRecord framing + CRC32C already implemented for the
TensorBoard writer (analytics_zoo_tpu/tensorboard/record.py, the
RecordWriter.scala role) is reused for READING, and a hand protobuf codec
(same approach as the ONNX loader's) decodes tf.train.Example, so ImageNet
TFRecord shards feed training with zero tensorflow dependency.

Wire format (tensorflow/core/example/example.proto):
  Example  { features: Features = 1 }
  Features { feature: map<string, Feature> = 1 }
  Feature  { bytes_list = 1 | float_list = 2 | int64_list = 3 }
  BytesList{ value: repeated bytes = 1 }
  FloatList{ value: repeated float = 1 (packed or not) }
  Int64List{ value: repeated int64 = 1 (packed or not) }
"""

from __future__ import annotations

import struct
from typing import Callable, Sequence

import numpy as np

from analytics_zoo_tpu.tensorboard.record import (
    _field_bytes,
    _iter_fields,
    _varint,
    masked_crc,
    write_record,
)

__all__ = [
    "read_tfrecord_file", "parse_example", "encode_example",
    "tfrecord_loader", "imagenet_example_parser", "count_tfrecord_records",
]


def count_tfrecord_records(path: str) -> int:
    """Record count by walking the framing headers only — seeks past every
    payload, so sizing a shard costs ~16 bytes of IO per record (the cheap
    sizer for ShardedFeatureSet; no decode, no parse)."""
    import os

    n = 0
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while pos + 8 <= size:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            pos += 8 + 4 + length + 4
            f.seek(pos)
            n += 1
    return n


# Buffered-read chunk: the framing walk used to issue FOUR tiny f.read
# calls per record (8B header, 4B crc, payload, 4B crc) — pure-python
# decode was syscall-bound before a single byte was parsed.  Reading the
# file in 1 MiB slabs and slicing records out of the buffer amortizes IO
# to ~one read per MiB.
_READ_CHUNK = 1 << 20


def _iter_frames(f, chunk_size: int = _READ_CHUNK, strict: bool = False):
    """Yield ``(header, hcrc, payload, dcrc)`` framing tuples from a
    binary stream using chunked buffered reads (no per-record syscalls).

    ``strict=False``: a truncated trailing record is dropped (the lenient
    read path); ``strict=True``: truncation raises — a caller asking for
    CRC verification must not get a silently shortened stream."""
    buf = bytearray()
    pos = 0
    eof = False

    def ensure(n: int) -> bool:
        nonlocal buf, pos, eof
        while len(buf) - pos < n and not eof:
            chunk = f.read(max(chunk_size, n))
            if not chunk:
                eof = True
                break
            if pos:
                del buf[:pos]
                pos = 0
            buf += chunk
        return len(buf) - pos >= n

    while True:
        if not ensure(12):
            if strict and len(buf) - pos > 0:
                raise ValueError("truncated record header")
            return
        header = bytes(buf[pos:pos + 8])
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack_from("<I", buf, pos + 8)
        pos += 12
        if not ensure(length + 4):
            if strict:
                raise ValueError("truncated record payload")
            return
        payload = bytes(buf[pos:pos + length])
        (dcrc,) = struct.unpack_from("<I", buf, pos + length)
        pos += length + 4
        yield header, hcrc, payload, dcrc


def read_tfrecord_file(path: str, verify_crc: bool = False,
                       chunk_size: int = _READ_CHUNK):
    """Yield raw record bytes from one TFRecord file (buffered: the file
    is read in ``chunk_size`` slabs, not four tiny reads per record).

    ``verify_crc=True`` checks the masked CRC32C of every record payload
    (the framing the reference writes via RecordWriter.scala; one shared
    table-driven CRC — record.py's — serves every record)."""
    with open(path, "rb") as f:
        try:
            for header, hcrc, data, dcrc in _iter_frames(
                    f, chunk_size, strict=verify_crc):
                if verify_crc:
                    if masked_crc(header) != hcrc:
                        raise ValueError(f"{path}: corrupt record header")
                    if masked_crc(data) != dcrc:
                        raise ValueError(
                            f"{path}: corrupt record payload")
                yield data
        except ValueError as e:
            if str(e).startswith("truncated"):
                raise ValueError(f"{path}: {e}") from None
            raise


def _decode_list(data: bytes, wire_hint: str):
    """Decode BytesList/FloatList/Int64List bodies (field 1, repeated)."""
    out = []
    for num, wire, val in _iter_fields(data):
        if num != 1:
            continue
        if wire_hint == "bytes":
            out.append(val)
        elif wire_hint == "float":
            if wire == 2:  # packed
                out.extend(np.frombuffer(val, "<f4").tolist())
            else:
                out.append(struct.unpack("<f", val)[0])
        else:  # int64
            if wire == 2:  # packed varints
                i = 0
                while i < len(val):
                    v = 0
                    shift = 0
                    while True:
                        b = val[i]
                        i += 1
                        v |= (b & 0x7F) << shift
                        if not b & 0x80:
                            break
                        shift += 7
                    if v >= 1 << 63:
                        v -= 1 << 64
                    out.append(v)
            else:
                if val >= 1 << 63:
                    val -= 1 << 64
                out.append(val)
    return out


def parse_example(data: bytes) -> dict:
    """tf.train.Example bytes -> {name: list_of_values}.

    bytes features decode to ``bytes``; float/int64 features to python
    numbers — the caller's parse_fn shapes them (the role the reference
    delegates to user TF graph code in TFBytesDataset)."""
    out = {}
    for num, wire, val in _iter_fields(data):
        if num != 1 or wire != 2:
            continue  # Example.features
        for n2, w2, feat_map in _iter_fields(val):
            if n2 != 1 or w2 != 2:
                continue  # Features.feature map entry
            key, feature = None, None
            for n3, w3, v3 in _iter_fields(feat_map):
                if n3 == 1:
                    key = v3.decode()
                elif n3 == 2:
                    feature = v3
            if key is None or feature is None:
                continue
            for n4, w4, v4 in _iter_fields(feature):
                kind = {1: "bytes", 2: "float", 3: "int64"}.get(n4)
                if kind is not None:
                    out[key] = _decode_list(v4, kind)
    return out


def _encode_list(kind: str, values) -> bytes:
    body = b""
    if kind == "bytes":
        for v in values:
            body += _field_bytes(1, bytes(v))
    elif kind == "float":
        packed = struct.pack(f"<{len(values)}f", *values)
        body += _field_bytes(1, packed)
    else:
        packed = b"".join(_varint(v & ((1 << 64) - 1)) for v in values)
        body += _field_bytes(1, packed)
    return body


def encode_example(features: dict) -> bytes:
    """{name: list|bytes|ndarray} -> tf.train.Example bytes (for writing
    shards and fixtures; the reference relies on external tooling)."""
    feats = b""
    for key, values in features.items():
        if isinstance(values, bytes):
            kind, values = "bytes", [values]
        elif isinstance(values, np.ndarray):
            if np.issubdtype(values.dtype, np.integer):
                kind, values = "int64", values.ravel().tolist()
            else:
                kind, values = "float", values.ravel().tolist()
        elif values and isinstance(values[0], (bytes, bytearray)):
            kind = "bytes"
        elif values and isinstance(values[0], int):
            kind = "int64"
        else:
            kind = "float"
        field_num = {"bytes": 1, "float": 2, "int64": 3}[kind]
        feature = _field_bytes(field_num, _encode_list(kind, values))
        entry = _field_bytes(1, key.encode()) + _field_bytes(2, feature)
        feats += _field_bytes(1, entry)
    # Example.features (field 1) wraps the Features message, whose content
    # is the series of map entries already in `feats`.
    return _field_bytes(1, feats)


def write_tfrecord_file(path: str, examples) -> None:
    """Write encoded Example byte strings as a TFRecord file."""
    with open(path, "wb") as f:
        for ex in examples:
            write_record(f, ex)


def imagenet_example_parser(image_key: str = "image/encoded",
                            label_key: str = "image/class/label",
                            label_offset: int = 0,
                            image_size: int | None = None) -> Callable:
    """Parser for ImageNet-style TFRecords (JPEG bytes + int label) -> the
    (x, y) arrays FeatureSet batches carry.  ``image_size`` optionally
    resizes at load (uint8 out, normalization stays on device)."""

    def parse(feature_map: dict):
        import cv2

        buf = np.frombuffer(feature_map[image_key][0], np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)[:, :, ::-1]  # RGB
        if image_size is not None:
            img = cv2.resize(img, (image_size, image_size),
                             interpolation=cv2.INTER_AREA)
        label = int(feature_map[label_key][0]) + label_offset
        return img.astype(np.uint8), np.int32(label)

    return parse


def tfrecord_loader(parse_fn: Callable) -> Callable:
    """Build a ShardedFeatureSet loader: one TFRecord file -> {"x", "y"}.

    ``parse_fn(feature_map) -> (x, y)`` per record."""

    def load(path: str) -> dict:
        xs, ys = [], []
        for rec in read_tfrecord_file(path):
            x, y = parse_fn(parse_example(rec))
            xs.append(x)
            ys.append(y)
        return {"x": np.stack(xs), "y": np.stack(ys)}

    return load
