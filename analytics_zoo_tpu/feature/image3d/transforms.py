"""3D medical-image transforms.

Reference: feature/image3d/*.scala — ``AffineTransform3D`` (matrix warp with
trilinear resampling), ``Crop3D``/``CenterCrop3D``/``RandomCrop3D``, and
``Rotate3D`` (Euler-angle rotation about the volume center).  SURVEY.md §2.1
lists these as part of the data layer's capability contract.

Volumes are numpy (D, H, W) or (D, H, W, C); transforms are host-side
``Preprocessing`` stages (composable with ``>>``) like the 2D pipeline.
"""

from __future__ import annotations

import math

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.image.transforms import _RandomOp


def _as_volume(t):
    v = np.asarray(t)
    if v.ndim not in (3, 4):
        raise ValueError(f"expected (D,H,W[,C]) volume, got {v.shape}")
    return v


def trilinear_sample(vol: np.ndarray, coords: np.ndarray) -> np.ndarray:
    """Sample ``vol`` (D,H,W[,C]) at fractional ``coords`` (..., 3) in
    (d, h, w) order with trilinear interpolation; out-of-range reads clamp
    to the border."""
    squeeze = vol.ndim == 3
    if squeeze:
        vol = vol[..., None]
    d, h, w, c = vol.shape
    cd = np.clip(coords[..., 0], 0, d - 1)
    ch = np.clip(coords[..., 1], 0, h - 1)
    cw = np.clip(coords[..., 2], 0, w - 1)
    d0, h0, w0 = np.floor(cd).astype(int), np.floor(ch).astype(int), \
        np.floor(cw).astype(int)
    d1 = np.minimum(d0 + 1, d - 1)
    h1 = np.minimum(h0 + 1, h - 1)
    w1 = np.minimum(w0 + 1, w - 1)
    fd = (cd - d0)[..., None]
    fh = (ch - h0)[..., None]
    fw = (cw - w0)[..., None]
    vf = vol.astype(np.float32)
    out = (
        vf[d0, h0, w0] * (1 - fd) * (1 - fh) * (1 - fw)
        + vf[d1, h0, w0] * fd * (1 - fh) * (1 - fw)
        + vf[d0, h1, w0] * (1 - fd) * fh * (1 - fw)
        + vf[d0, h0, w1] * (1 - fd) * (1 - fh) * fw
        + vf[d1, h1, w0] * fd * fh * (1 - fw)
        + vf[d1, h0, w1] * fd * (1 - fh) * fw
        + vf[d0, h1, w1] * (1 - fd) * fh * fw
        + vf[d1, h1, w1] * fd * fh * fw
    )
    return out[..., 0] if squeeze else out


def rotation_matrix_3d(yaw: float = 0.0, pitch: float = 0.0,
                       roll: float = 0.0) -> np.ndarray:
    """Euler rotation (about volume axes d, h, w) -> 3x3 matrix."""
    cy, sy = math.cos(yaw), math.sin(yaw)
    cp, sp = math.cos(pitch), math.sin(pitch)
    cr, sr = math.cos(roll), math.sin(roll)
    rz = np.array([[1, 0, 0], [0, cy, -sy], [0, sy, cy]])
    ry = np.array([[cp, 0, sp], [0, 1, 0], [-sp, 0, cp]])
    rx = np.array([[cr, -sr, 0], [sr, cr, 0], [0, 0, 1]])
    return (rz @ ry @ rx).astype(np.float64)


class AffineTransform3D(Preprocessing):
    """Resample through an affine map about the volume center
    (reference AffineTransform3D: out(x) = vol(A⁻¹(x - c) + c + t))."""

    def __init__(self, matrix: np.ndarray, translation=(0.0, 0.0, 0.0)):
        self.matrix = np.asarray(matrix, np.float64).reshape(3, 3)
        self.translation = np.asarray(translation, np.float64)

    def transform(self, vol):
        vol = _as_volume(vol)
        d, h, w = vol.shape[:3]
        center = (np.array([d, h, w], np.float64) - 1) / 2.0
        grid = np.stack(np.meshgrid(
            np.arange(d), np.arange(h), np.arange(w), indexing="ij"
        ), axis=-1).astype(np.float64)
        inv = np.linalg.inv(self.matrix)
        coords = (grid - center) @ inv.T + center + self.translation
        return trilinear_sample(vol, coords)


class Rotate3D(AffineTransform3D):
    """Reference Rotate3D: Euler-angle rotation, trilinear resample."""

    def __init__(self, yaw=0.0, pitch=0.0, roll=0.0):
        super().__init__(rotation_matrix_3d(yaw, pitch, roll))


class Warp3D(Preprocessing):
    """Dense flow-field warp (reference Warp.scala ``WarpTransformer``).

    ``flow_field``: (3, D', H', W') array of (flow_z, flow_y, flow_x); the
    output volume has the flow field's spatial shape.  With ``offset=True``
    the flow is added to the (1-based, matching the reference's Tensor
    indexing) target coordinate; with ``offset=False`` the flow IS the
    absolute source coordinate.  ``clamp_mode="clamp"`` clamps off-image
    samples to the border; ``"padding"`` writes ``pad_val`` instead.
    Interpolation is trilinear with the reference's exact border rule
    (ceil index saturates at the last voxel).  Vectorized numpy instead of
    the reference's per-voxel triple loop.
    """

    def __init__(self, flow_field, offset: bool = True,
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        self.flow = np.asarray(flow_field, np.float64)
        if self.flow.ndim != 4 or self.flow.shape[0] != 3:
            raise ValueError(
                f"flow_field must be (3, D, H, W), got {self.flow.shape}")
        if clamp_mode not in ("clamp", "padding"):
            raise ValueError(f"clamp_mode {clamp_mode!r}")
        self.offset = bool(offset)
        self.clamp_mode = clamp_mode
        self.pad_val = float(pad_val)

    def transform(self, vol):
        vol = _as_volume(vol)
        squeeze = vol.ndim == 3
        vf = (vol if not squeeze else vol[..., None]).astype(np.float32)
        sd, sh, sw = vf.shape[:3]
        _, dd, dh, dw = self.flow.shape
        # 1-based target grid, reference Tensor indexing
        z, y, x = np.meshgrid(np.arange(1, dd + 1), np.arange(1, dh + 1),
                              np.arange(1, dw + 1), indexing="ij")
        om = 1.0 if self.offset else 0.0
        iz = om * z + self.flow[0]
        iy = om * y + self.flow[1]
        ix = om * x + self.flow[2]
        off_image = ((iz < 1) | (iz > sd) | (iy < 1) | (iy > sh)
                     | (ix < 1) | (ix > sw))
        iz = np.clip(iz, 1, sd)
        iy = np.clip(iy, 1, sh)
        ix = np.clip(ix, 1, sw)
        iz0 = np.floor(iz).astype(int)
        iy0 = np.floor(iy).astype(int)
        ix0 = np.floor(ix).astype(int)
        iz1 = np.minimum(iz0 + 1, sd)
        iy1 = np.minimum(iy0 + 1, sh)
        ix1 = np.minimum(ix0 + 1, sw)
        wz = (iz - iz0)[..., None]
        wy = (iy - iy0)[..., None]
        wx = (ix - ix0)[..., None]
        g = lambda a, b, c: vf[a - 1, b - 1, c - 1]  # noqa: E731 (1-based)
        out = (
            (1 - wy) * (1 - wx) * (1 - wz) * g(iz0, iy0, ix0)
            + (1 - wy) * (1 - wx) * wz * g(iz1, iy0, ix0)
            + (1 - wy) * wx * (1 - wz) * g(iz0, iy0, ix1)
            + (1 - wy) * wx * wz * g(iz1, iy0, ix1)
            + wy * (1 - wx) * (1 - wz) * g(iz0, iy1, ix0)
            + wy * (1 - wx) * wz * g(iz1, iy1, ix0)
            + wy * wx * (1 - wz) * g(iz0, iy1, ix1)
            + wy * wx * wz * g(iz1, iy1, ix1)
        )
        if self.clamp_mode == "padding":
            out = np.where(off_image[..., None], self.pad_val, out)
        out = out.astype(np.float32)
        return out[..., 0] if squeeze else out


class Crop3D(Preprocessing):
    """Crop ``patch_size`` starting at ``start`` (reference Crop3D)."""

    def __init__(self, start, patch_size):
        self.start = tuple(int(s) for s in start)
        self.patch = tuple(int(s) for s in patch_size)

    def transform(self, vol):
        vol = _as_volume(vol)
        (d0, h0, w0), (pd, ph, pw) = self.start, self.patch
        if (d0 < 0 or h0 < 0 or w0 < 0 or d0 + pd > vol.shape[0]
                or h0 + ph > vol.shape[1] or w0 + pw > vol.shape[2]):
            raise ValueError(
                f"crop {self.start}+{self.patch} outside volume "
                f"{vol.shape[:3]}")
        return vol[d0:d0 + pd, h0:h0 + ph, w0:w0 + pw]


class CenterCrop3D(Preprocessing):
    def __init__(self, patch_size):
        self.patch = tuple(int(s) for s in patch_size)

    def transform(self, vol):
        vol = _as_volume(vol)
        start = [(s - p) // 2 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch)(vol)


class RandomCrop3D(_RandomOp):
    def __init__(self, patch_size):
        super().__init__()
        self.patch = tuple(int(s) for s in patch_size)

    def transform(self, vol):
        vol = _as_volume(vol)
        rng = self.next_rng()
        start = [int(rng.integers(0, s - p + 1))
                 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch)(vol)
