"""3D (medical) image transforms — reference zoo/.../feature/image3d
(AffineTransform3D, Crop3D variants, Rotate3D, Warp.scala flow-field
warp)."""

from analytics_zoo_tpu.feature.image3d.transforms import (
    AffineTransform3D,
    CenterCrop3D,
    Crop3D,
    RandomCrop3D,
    Rotate3D,
    Warp3D,
    rotation_matrix_3d,
)

__all__ = [
    "AffineTransform3D",
    "Crop3D",
    "CenterCrop3D",
    "RandomCrop3D",
    "Rotate3D",
    "Warp3D",
    "rotation_matrix_3d",
]
