from analytics_zoo_tpu.feature.common import (  # noqa: F401
    ChainedPreprocessing,
    Preprocessing,
)
from analytics_zoo_tpu.feature.dataset import FeatureSet  # noqa: F401
from analytics_zoo_tpu.feature.prefetch import (  # noqa: F401
    PrefetchFeatureSet,
    PrefetchPipeline,
)
