"""TextSet — text dataset with the tokenize→normalize→word2idx→shape
pipeline and relation pairs/lists for ranking.

Reference: zoo/.../feature/text/TextSet.scala:43-630 (``tokenize`` :97,
``normalize``, ``word2idx`` :147, ``shapeSequence``, ``generateSample``,
``fromRelationPairs`` :399, ``fromRelationLists``), TextFeature.scala, and
the transformer classes under feature/text/*.scala.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from analytics_zoo_tpu.feature.dataset import ArrayFeatureSet, FeatureSet

_TOKEN_RE = re.compile(r"[^a-zA-Z0-9]+")


@dataclass
class TextFeature:
    """One text record (reference TextFeature.scala): raw text + evolving
    fields as the pipeline runs."""

    text: str
    label: int | None = None
    tokens: list[str] | None = None
    indices: np.ndarray | None = None
    uri: str | None = None


@dataclass
class Relation:
    """Query-document relation (reference text/Relation)."""

    id1: str
    id2: str
    label: int


def read_relations_csv(path: str, sep: str = ",") -> list[Relation]:
    """id1,id2,label per line (reference Relations.read,
    feature/common/Relations.scala:43-76); a header line is skipped,
    malformed data lines raise (silent drops would shrink the training
    relation set unnoticed)."""
    out = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            stripped = line.rstrip("\n")
            if not stripped:
                continue
            parts = stripped.split(sep)
            if lineno == 1 and parts[-1] == "label":
                continue  # header
            if len(parts) != 3:
                raise ValueError(
                    f"{path}:{lineno}: expected id1{sep}id2{sep}label, "
                    f"got {stripped!r}")
            out.append(Relation(parts[0], parts[1], int(parts[2])))
    return out


def read_relations_parquet(path: str) -> list[Relation]:
    """Relations from a parquet file with schema "id1"(str), "id2"(str),
    "label"(int) — reference Relations.readParquet
    (feature/common/Relations.scala:78)."""
    import pandas as pd

    df = pd.read_parquet(path)
    return [Relation(str(a), str(b), int(c))
            for a, b, c in zip(df["id1"], df["id2"], df["label"])]


class TextSet:
    """Pipeline container (reference TextSet.scala).  All stages return a
    new TextSet; ``word_index`` is built by word2idx and reusable across
    train/test (``setWordIndex`` semantics)."""

    def __init__(self, features: Sequence[TextFeature],
                 word_index: dict[str, int] | None = None):
        self.features = list(features)
        self.word_index = word_index

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_texts(texts: Iterable[str], labels=None) -> "TextSet":
        labels = list(labels) if labels is not None else None
        return TextSet([
            TextFeature(t, None if labels is None else int(labels[i]))
            for i, t in enumerate(texts)
        ])

    @staticmethod
    def read_csv(path: str, sep: str = ",") -> "TextSet":
        """uri,text per line (reference TextSet.readCSV)."""
        feats = []
        with open(path) as f:
            for line in f:
                uri, text = line.rstrip("\n").split(sep, 1)
                feats.append(TextFeature(text, uri=uri))
        return TextSet(feats)

    @staticmethod
    def read_parquet(path: str) -> "TextSet":
        """Read texts with id from a parquet file with schema
        "id"(str), "text"(str) — reference TextSet.readParquet
        (TextSet.scala:372); pandas/pyarrow stands in for SQLContext."""
        import pandas as pd

        df = pd.read_parquet(path)
        return TextSet([
            TextFeature(str(text), uri=str(uri))
            for uri, text in zip(df["id"], df["text"])
        ])

    # -- pipeline stages ---------------------------------------------------
    def tokenize(self) -> "TextSet":
        """Reference TextSet.tokenize (:97)."""
        for f in self.features:
            f.tokens = [t for t in _TOKEN_RE.split(f.text) if t]
        return self

    def normalize(self) -> "TextSet":
        for f in self.features:
            assert f.tokens is not None, "tokenize first"
            f.tokens = [t.lower() for t in f.tokens]
        return self

    def word2idx(self, remove_topn: int = 0,
                 max_words_num: int = -1,
                 existing_map: dict[str, int] | None = None) -> "TextSet":
        """Build (or reuse) the word index; 1-based, 0 reserved for padding
        (reference TextSet.word2idx :147 semantics)."""
        if existing_map is None and self.word_index is None:
            freq: dict[str, int] = {}
            for f in self.features:
                for t in f.tokens:
                    freq[t] = freq.get(t, 0) + 1
            ordered = sorted(freq.items(), key=lambda kv: -kv[1])
            ordered = ordered[remove_topn:]
            if max_words_num > 0:
                ordered = ordered[:max_words_num]
            self.word_index = {w: i + 1 for i, (w, _) in enumerate(ordered)}
        elif existing_map is not None:
            self.word_index = dict(existing_map)
        for f in self.features:
            f.indices = np.asarray(
                [self.word_index.get(t, 0) for t in f.tokens], np.int32
            )
        return self

    def shape_sequence(self, length: int, mode: str = "pre") -> "TextSet":
        """Pad (with 0) / truncate to fixed length (reference
        SequenceShaper.scala; trunc_mode pre/post)."""
        for f in self.features:
            idx = f.indices
            if len(idx) >= length:
                f.indices = idx[-length:] if mode == "pre" else idx[:length]
            else:
                pad = np.zeros(length - len(idx), np.int32)
                f.indices = np.concatenate([pad, idx]) if mode == "pre" \
                    else np.concatenate([idx, pad])
        return self

    def generate_sample(self) -> "TextSet":
        return self  # indices already materialized; parity no-op

    # -- exports -----------------------------------------------------------
    def to_feature_set(self) -> FeatureSet:
        x = np.stack([f.indices for f in self.features])
        labels = [f.label for f in self.features]
        y = None if any(l is None for l in labels) \
            else np.asarray(labels, np.int32)
        return ArrayFeatureSet(x, y)

    def get_word_index(self) -> dict[str, int]:
        return dict(self.word_index or {})

    def set_word_index(self, vocab: dict[str, int]) -> "TextSet":
        """Assign a word index to use during word2idx (reference
        TextSet.setWordIndex, TextSet.scala:207)."""
        self.word_index = dict(vocab)
        return self

    def save_word_index(self, path: str) -> None:
        """Save the word index as "word id" lines for future inference
        (reference TextSet.saveWordIndex, TextSet.scala:222/687)."""
        if not self.word_index:
            raise ValueError(
                "wordIndex is None, nothing to save. Please transform "
                "from word to index first")
        with open(path, "w") as f:
            for word, idx in self.word_index.items():
                f.write(f"{word} {idx}\n")

    def load_word_index(self, path: str) -> "TextSet":
        """Load a saved "word id" index so word2idx reuses it exactly
        (reference TextSet.loadWordIndex, TextSet.scala:243/698)."""
        vocab = {}
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                word, idx = line.rsplit(" ", 1)
                vocab[word] = int(idx)
        return self.set_word_index(vocab)

    def __len__(self):
        return len(self.features)

    # -- relations (ranking) ----------------------------------------------
    @staticmethod
    def from_relation_pairs(relations: Sequence[Relation],
                            corpus1: "TextSet", corpus2: "TextSet",
                            ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Build interleaved (pos, neg) pair arrays for RankHinge training
        (reference TextSet.fromRelationPairs :399): for each query, every
        (pos, neg) doc combination yields two consecutive rows."""
        t1 = {f.uri: f.indices for f in corpus1.features}
        t2 = {f.uri: f.indices for f in corpus2.features}
        by_query: dict[str, dict[int, list[str]]] = {}
        for r in relations:
            by_query.setdefault(r.id1, {}).setdefault(
                int(r.label > 0), []).append(r.id2)
        qs, ds, ys = [], [], []
        for q, groups in by_query.items():
            for pos in groups.get(1, []):
                for neg in groups.get(0, []):
                    qs += [t1[q], t1[q]]
                    ds += [t2[pos], t2[neg]]
                    ys += [1, 0]
        return (np.stack(qs), np.stack(ds),
                np.asarray(ys, np.float32)[:, None])

    @staticmethod
    def from_relation_lists(relations: Sequence[Relation],
                            corpus1: "TextSet", corpus2: "TextSet"):
        """Grouped candidate lists for NDCG/MAP evaluation (reference
        TextSet.fromRelationLists): per query → (q_array, d_array,
        labels)."""
        t1 = {f.uri: f.indices for f in corpus1.features}
        t2 = {f.uri: f.indices for f in corpus2.features}
        by_query: dict[str, list[Relation]] = {}
        for r in relations:
            by_query.setdefault(r.id1, []).append(r)
        out = []
        for q, rels in by_query.items():
            qa = np.stack([t1[q]] * len(rels))
            da = np.stack([t2[r.id2] for r in rels])
            labels = np.asarray([r.label for r in rels], np.float32)
            out.append((qa, da, labels))
        return out
