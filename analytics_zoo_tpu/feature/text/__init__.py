from analytics_zoo_tpu.feature.text.textset import (  # noqa: F401
    Relation,
    TextFeature,
    TextSet,
    read_relations_csv,
    read_relations_parquet,
)
