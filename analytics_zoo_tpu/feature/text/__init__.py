from analytics_zoo_tpu.feature.text.textset import (  # noqa: F401
    Relation,
    TextFeature,
    TextSet,
)
