"""ROI (detection-aware) image transforms + the SSD training pipeline.

Reference: the SSD train/val chains in
zoo/.../models/image/objectdetection/ssd/SSDDataSet.scala:44-53,70-76
(RoiRecordToFeature -> ImageRoiNormalize -> ImageColorJitter ->
 random(ImageExpand -> ImageRoiProject) -> ImageRandomSampler ->
 ImageResize -> random(ImageHFlip -> ImageRoiHFlip) ->
 ImageChannelNormalize -> batch) and the box-preserving ops under
zoo/.../feature/image/ (ImageExpand.scala, RandomSampler.scala,
RoiTransformer.scala) backed by BigDL's roi label transformers.

A **roi record** is a dict:
  ``image``     uint8/float32 (H, W, 3) RGB
  ``boxes``     float32 (N, 4) corners — pixel coords until
                :class:`ImageRoiNormalize` makes them relative [0,1]
  ``classes``   float32 (N,) 1-based class ids (0 = background)
  ``difficult`` float32 (N,) 0/1 flags
  ``_rng``      np.random.Generator injected per-record by
                :class:`RoiFeatureSet` so augmentation is seeded and
                resumable (the reference uses a global RNG and is not).

All ops are host-side per-record (SURVEY.md §7: host assembles compact
batches; device does the math).
"""

from __future__ import annotations

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.dataset import FeatureSet

__all__ = [
    "ImageRoiNormalize", "ImageColorJitter", "ImageExpandRoi",
    "ImageRandomSampler", "ImageRoiResize", "ImageRoiHFlip",
    "ImageRoiChannelNormalize", "RoiFeatureSet", "ssd_train_set",
    "ssd_val_set",
]


def _rng_of(record) -> np.random.Generator:
    rng = record.get("_rng")
    if rng is None:
        rng = np.random.default_rng()
        record["_rng"] = rng
    return rng


class ImageRoiNormalize(Preprocessing):
    """Pixel-corner boxes -> relative [0,1] (BigDL RoiNormalize; used at
    SSDDataSet.scala:45)."""

    def transform(self, record):
        h, w = record["image"].shape[:2]
        boxes = np.asarray(record["boxes"], np.float32).reshape(-1, 4).copy()
        boxes[:, [0, 2]] /= float(w)
        boxes[:, [1, 3]] /= float(h)
        record["boxes"] = boxes
        return record


class ImageColorJitter(Preprocessing):
    """Brightness/contrast/saturation jitter in random order (reference
    ImageColorJitter.scala -> BigDL ColorJitter defaults)."""

    def __init__(self, brightness_delta=32.0, contrast=(0.5, 1.5),
                 saturation=(0.5, 1.5), prob=0.5):
        self.brightness_delta = brightness_delta
        self.contrast = contrast
        self.saturation = saturation
        self.prob = prob

    def transform(self, record):
        rng = _rng_of(record)
        img = record["image"].astype(np.float32)

        def bright(im):
            if rng.random() < self.prob:
                im = im + rng.uniform(-self.brightness_delta,
                                      self.brightness_delta)
            return im

        def contrast(im):
            if rng.random() < self.prob:
                im = im * rng.uniform(*self.contrast)
            return im

        def sat(im):
            if rng.random() < self.prob:
                gray = im.mean(axis=2, keepdims=True)
                im = gray + (im - gray) * rng.uniform(*self.saturation)
            return im

        ops = [bright, contrast, sat]
        rng.shuffle(ops)
        for op in ops:
            img = op(img)
        record["image"] = np.clip(img, 0, 255).astype(np.uint8)
        return record


class ImageExpandRoi(Preprocessing):
    """Zoom-out: place the image on a mean-filled canvas of ratio
    [1, max_ratio], projecting boxes (reference ImageExpand.scala +
    ImageRoiProject, applied with prob 0.5 at SSDDataSet.scala:47)."""

    def __init__(self, max_expand_ratio=4.0, means=(123, 117, 104),
                 prob=0.5):
        self.max_ratio = float(max_expand_ratio)
        self.means = np.asarray(means, np.float32)
        self.prob = prob

    def transform(self, record):
        rng = _rng_of(record)
        if rng.random() >= self.prob:
            return record
        img = record["image"]
        h, w = img.shape[:2]
        ratio = rng.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        top = int(rng.uniform(0, nh - h))
        left = int(rng.uniform(0, nw - w))
        canvas = np.empty((nh, nw, 3), img.dtype)
        canvas[...] = self.means.astype(img.dtype)
        canvas[top:top + h, left:left + w] = img
        record["image"] = canvas
        boxes = record["boxes"].copy()  # relative coords
        boxes[:, [0, 2]] = (boxes[:, [0, 2]] * w + left) / nw
        boxes[:, [1, 3]] = (boxes[:, [1, 3]] * h + top) / nh
        record["boxes"] = boxes
        return record


def _iou_one_many(box, boxes):
    ix1 = np.maximum(box[0], boxes[:, 0])
    iy1 = np.maximum(box[1], boxes[:, 1])
    ix2 = np.minimum(box[2], boxes[:, 2])
    iy2 = np.minimum(box[3], boxes[:, 3])
    iw = np.clip(ix2 - ix1, 0, None)
    ih = np.clip(iy2 - iy1, 0, None)
    inter = iw * ih
    a = (box[2] - box[0]) * (box[3] - box[1])
    b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return inter / np.maximum(a + b - inter, 1e-12)


class ImageRandomSampler(Preprocessing):
    """SSD batch-sampled crop (reference ImageRandomSampler ->
    BigDL RandomSampler: one 'keep whole image' sampler plus one sampler
    per min-IoU in {0.1, 0.3, 0.5, 0.7, 0.9}, each up to ``max_trials``
    attempts at scale [0.3,1], aspect [0.5,2]; one sampled crop is chosen
    at random; boxes are kept iff their center lies in the crop, then
    projected and clipped)."""

    MIN_IOUS = (0.1, 0.3, 0.5, 0.7, 0.9)

    def __init__(self, max_trials=50, min_scale=0.3, max_scale=1.0,
                 min_aspect=0.5, max_aspect=2.0):
        self.max_trials = max_trials
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.min_aspect = min_aspect
        self.max_aspect = max_aspect

    def _sample_box(self, rng, boxes, min_iou):
        for _ in range(self.max_trials):
            scale = rng.uniform(self.min_scale, self.max_scale)
            ar = rng.uniform(max(self.min_aspect, scale ** 2),
                            min(self.max_aspect, 1.0 / scale ** 2))
            bw = scale * np.sqrt(ar)
            bh = scale / np.sqrt(ar)
            x = rng.uniform(0, 1 - bw)
            y = rng.uniform(0, 1 - bh)
            crop = np.array([x, y, x + bw, y + bh], np.float32)
            if len(boxes) == 0:
                return crop
            if _iou_one_many(crop, boxes).max() >= min_iou:
                return crop
        return None

    def transform(self, record):
        rng = _rng_of(record)
        boxes = record["boxes"]
        sampled = [None]  # the "whole image" sampler
        for miou in self.MIN_IOUS:
            got = self._sample_box(rng, boxes, miou)
            if got is not None:
                sampled.append(got)
        crop = sampled[rng.integers(len(sampled))]
        if crop is None:
            return record
        img = record["image"]
        h, w = img.shape[:2]
        x1, y1, x2, y2 = crop
        px1, py1 = int(x1 * w), int(y1 * h)
        px2, py2 = max(px1 + 1, int(x2 * w)), max(py1 + 1, int(y2 * h))
        record["image"] = img[py1:py2, px1:px2]
        if len(boxes):
            centers = (boxes[:, :2] + boxes[:, 2:]) / 2
            keep = ((centers[:, 0] >= x1) & (centers[:, 0] <= x2)
                    & (centers[:, 1] >= y1) & (centers[:, 1] <= y2))
            boxes = boxes[keep].copy()
            cw, ch = x2 - x1, y2 - y1
            boxes[:, [0, 2]] = np.clip((boxes[:, [0, 2]] - x1) / cw, 0, 1)
            boxes[:, [1, 3]] = np.clip((boxes[:, [1, 3]] - y1) / ch, 0, 1)
            record["boxes"] = boxes
            record["classes"] = record["classes"][keep]
            record["difficult"] = record["difficult"][keep]
        return record


class ImageRoiResize(Preprocessing):
    """Resize to a fixed resolution; relative boxes are untouched
    (reference ImageResize at SSDDataSet.scala:49)."""

    def __init__(self, width: int, height: int):
        self.width, self.height = int(width), int(height)

    def transform(self, record):
        import cv2

        record["image"] = cv2.resize(
            record["image"], (self.width, self.height),
            interpolation=cv2.INTER_LINEAR)
        return record


class ImageRoiHFlip(Preprocessing):
    """Horizontal flip of image + boxes with prob (reference
    ImageHFlip -> ImageRoiHFlip under ImageRandomPreprocessing 0.5)."""

    def __init__(self, prob=0.5):
        self.prob = prob

    def transform(self, record):
        rng = _rng_of(record)
        if rng.random() >= self.prob:
            return record
        record["image"] = record["image"][:, ::-1]
        boxes = record["boxes"].copy()
        boxes[:, [0, 2]] = 1.0 - boxes[:, [2, 0]]
        record["boxes"] = boxes
        return record


class ImageRoiChannelNormalize(Preprocessing):
    """Subtract per-channel means (reference ImageChannelNormalize(123,
    117, 104) at SSDDataSet.scala:52); output float32."""

    def __init__(self, means=(123, 117, 104), stds=None):
        self.means = np.asarray(means, np.float32)
        self.stds = None if stds is None else np.asarray(stds, np.float32)

    def transform(self, record):
        img = record["image"].astype(np.float32) - self.means
        if self.stds is not None:
            img = img / self.stds
        record["image"] = img
        return record


class RoiFeatureSet(FeatureSet):
    """FeatureSet over roi records with seeded per-record augmentation and
    SSDMiniBatch-style padding (variable gt counts -> fixed (max_boxes, 5)
    with label −1 padding; reference SSDMiniBatch.scala / RoiImageToSSDBatch).

    Iteration state is (seed, epoch, cursor) like every FeatureSet here —
    augmentation draws from a per-(record, epoch) generator, so resume
    replays identical batches (the reference's global-RNG pipeline cannot).
    """

    def __init__(self, records, chain: Preprocessing, max_boxes: int = 16,
                 keep_difficult: bool = True, label_offset: float = 0.0):
        self.records = list(records)
        self.chain = chain
        self.max_boxes = int(max_boxes)
        self.keep_difficult = keep_difficult
        # VOC-style annotations are 1-based with background=0
        # (PascalVoc.scala:88); MultiBoxLoss here takes 0-based foreground
        # ids with -1 padding, so VOC pipelines pass label_offset=-1.
        self.label_offset = float(label_offset)

    @property
    def num_samples(self):
        return len(self.records)

    def _materialize(self, ri: int, seed: int, epoch: int):
        rec = self.records[ri]
        image = rec.get("image")
        if image is None:
            # Lazy loading for full-scale datasets: PascalVoc/Coco
            # roidb(read_image=False) records carry only "path", so the
            # whole split is never resident at once (COCO train2017 would
            # be ~60 GB decoded).
            from PIL import Image

            with Image.open(rec["path"]) as im:
                image = np.asarray(im.convert("RGB"))
        rec = {
            "image": image,
            "boxes": np.asarray(rec["boxes"], np.float32).reshape(-1, 4),
            "classes": np.asarray(rec.get("classes", []), np.float32),
            "difficult": np.asarray(
                rec.get("difficult", np.zeros(len(rec["boxes"]))),
                np.float32),
            "_rng": np.random.default_rng(
                np.random.SeedSequence([seed, epoch, ri])),
        }
        rec = self.chain(rec)
        if not self.keep_difficult and len(rec["difficult"]):
            keep = rec["difficult"] == 0
            rec["boxes"] = rec["boxes"][keep]
            rec["classes"] = rec["classes"][keep]
        x = np.asarray(rec["image"], np.float32)
        y = np.full((self.max_boxes, 5), 0, np.float32)
        y[:, 4] = -1.0
        nb = min(len(rec["boxes"]), self.max_boxes)
        y[:nb, :4] = rec["boxes"][:nb]
        y[:nb, 4] = rec["classes"][:nb] + self.label_offset
        return x, y

    def batches(self, batch_size, shuffle=True, seed=0, epoch=0,
                drop_last=True, start_batch=0, pad_to_batch=None,
                process_shard=None):
        n = len(self.records)
        if shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([seed, epoch])).permutation(n)
        else:
            order = np.arange(n)
        n_batches = n // batch_size if drop_last else -(-n // batch_size)
        for b in range(start_batch, n_batches):
            idx = order[b * batch_size:(b + 1) * batch_size]
            n_valid = len(idx)
            if pad_to_batch is not None and n_valid % pad_to_batch != 0:
                pad = pad_to_batch - n_valid % pad_to_batch
                idx = np.concatenate([idx, np.repeat(idx[-1:], pad)])
            if process_shard is not None:
                # Slice BEFORE materializing: augmentation (cv2 resize,
                # sampling, jitter) runs only for this host's rows.
                from analytics_zoo_tpu.parallel.multihost import (
                    process_local_batch_slice,
                )
                idx = idx[process_local_batch_slice(len(idx), process_shard)]
            xs, ys = zip(*(self._materialize(int(ri), seed, epoch)
                           for ri in idx))
            batch = {"x": np.stack(xs), "y": np.stack(ys)}
            if pad_to_batch is not None:
                batch["n_valid"] = np.asarray(n_valid, np.int32)
            yield batch


def ssd_train_set(records, resolution: int = 300, max_boxes: int = 16,
                  means=(123, 117, 104), augment: bool = True,
                  scale: float | None = None,
                  label_offset: float = 0.0) -> RoiFeatureSet:
    """The SSD training pipeline (SSDDataSet.loadSSDTrainSet,
    SSDDataSet.scala:38-54), composed with ``>>``."""
    chain = ImageRoiNormalize()
    if augment:
        chain = (chain >> ImageColorJitter()
                 >> ImageExpandRoi(means=means, prob=0.5)
                 >> ImageRandomSampler())
    chain = chain >> ImageRoiResize(resolution, resolution)
    if augment:
        chain = chain >> ImageRoiHFlip(prob=0.5)
    stds = None if scale is None else (scale, scale, scale)
    chain = chain >> ImageRoiChannelNormalize(means, stds)
    return RoiFeatureSet(records, chain, max_boxes=max_boxes,
                         label_offset=label_offset)


def ssd_val_set(records, resolution: int = 300, max_boxes: int = 16,
                means=(123, 117, 104),
                label_offset: float = 0.0) -> RoiFeatureSet:
    """The SSD validation pipeline (SSDDataSet.loadSSDValSet,
    SSDDataSet.scala:64-77): no augmentation, difficult boxes kept."""
    return ssd_train_set(records, resolution, max_boxes, means,
                         augment=False, label_offset=label_offset)
