"""ImageSet — image dataset container (reference
feature/image/ImageSet.scala:46-134; ``read`` :236 loads local/distributed
folders).

Local folders of PNG/JPEG are decoded via PIL if available (pillow ships
with torch in this image), else raw ``.npy`` arrays are read.  A labeled
layout ``root/<class_name>/img`` yields integer labels like the reference's
``ImageSet.read(withLabel=true)``.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.dataset import ArrayFeatureSet, FeatureSet

_IMG_EXT = (".png", ".jpg", ".jpeg", ".bmp", ".npy")


def _decode(path: str) -> np.ndarray:
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            "PIL unavailable; use .npy images or install pillow"
        ) from e


class ImageSet:
    """In-memory image collection with label support + transform chaining."""

    def __init__(self, images: Sequence[np.ndarray],
                 labels: Sequence | None = None,
                 paths: Sequence[str] | None = None,
                 label_map: dict | None = None):
        self.images = list(images)
        self.labels = None if labels is None else list(labels)
        self.paths = paths
        self.label_map = label_map

    @staticmethod
    def read(path: str, with_label: bool = False,
             max_images: int | None = None) -> "ImageSet":
        """Reference ImageSet.read (ImageSet.scala:236)."""
        images, labels, paths = [], [], []
        label_map = None
        if with_label:
            classes = sorted(
                d for d in os.listdir(path)
                if os.path.isdir(os.path.join(path, d))
            )
            label_map = {c: i for i, c in enumerate(classes)}
            for c in classes:
                if max_images and len(images) >= max_images:
                    break
                for f in sorted(os.listdir(os.path.join(path, c))):
                    if f.lower().endswith(_IMG_EXT):
                        p = os.path.join(path, c, f)
                        images.append(_decode(p))
                        labels.append(label_map[c])
                        paths.append(p)
                        if max_images and len(images) >= max_images:
                            break
        else:
            for f in sorted(os.listdir(path)):
                if f.lower().endswith(_IMG_EXT):
                    p = os.path.join(path, f)
                    images.append(_decode(p))
                    paths.append(p)
                    if max_images and len(images) >= max_images:
                        break
        return ImageSet(images, labels if with_label else None, paths,
                        label_map)

    @staticmethod
    def from_arrays(images, labels=None) -> "ImageSet":
        return ImageSet(list(images), None if labels is None else
                        list(labels))

    def transform(self, preprocessing: Preprocessing) -> "ImageSet":
        """Apply a transform chain eagerly (reference
        ImageSet.transform)."""
        return ImageSet([preprocessing(img) for img in self.images],
                        self.labels, self.paths, self.label_map)

    def to_feature_set(self) -> FeatureSet:
        x = np.stack([np.asarray(i, np.float32) for i in self.images])
        y = None if self.labels is None else np.asarray(self.labels)
        return ArrayFeatureSet(x, y)

    def __len__(self):
        return len(self.images)
