"""Image preprocessing ops — host-side, numpy/C++-backed, feeding infeed.

Reference: zoo/.../feature/image/ImageProcessing.scala + the ~25
OpenCV-backed ops under feature/image (resize, crop variants, flip, hue,
saturation, brightness, normalize, expand, jitter — SURVEY.md §2.1).  The
reference runs OpenCV via BigDL's JNI; here the per-record ops are numpy
(uint8 in, float32 out at the normalize boundary), with the normalize hot
loop optionally served by the C++ library
(analytics_zoo_tpu/native/zoonative.cpp).  Records are HWC uint8/float
numpy arrays; all ops are `Preprocessing` stages composing with ``>>``.

Geometric ops use seeded per-record RNG derived from a records counter so a
transformed FeatureSet remains reproducible/checkpointable (the reference's
OpenCV ops were non-deterministic across retries).
"""

from __future__ import annotations

import zlib

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing


def _rng_for(record_seed):
    return np.random.default_rng(record_seed)


_random_op_instances = 0


class _RandomOp(Preprocessing):
    """Base for randomized ops: derives an rng from a per-record counter.

    The seed mixes a stable hash of the class name with a process-wide
    instance index, so streams are (a) reproducible across process restarts
    (checkpoint resume replays the same augmentations) and (b) independent
    between instances of the same op class.
    """

    def __init__(self):
        global _random_op_instances
        _random_op_instances += 1
        self._instance = _random_op_instances
        self._class_seed = zlib.crc32(type(self).__name__.encode())
        self._counter = 0

    def next_rng(self):
        self._counter += 1
        return np.random.default_rng(
            (self._class_seed, self._instance, self._counter))


class ImageResize(Preprocessing):
    """Bilinear resize to (height, width) (reference image/Resize)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def transform(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        if (h, w) == (self.h, self.w):
            return img
        # bilinear via coordinate sampling (no cv2 dependency)
        ys = np.linspace(0, h - 1, self.h)
        xs = np.linspace(0, w - 1, self.w)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img_f = img.astype(np.float32)
        top = img_f[y0][:, x0] * (1 - wx) + img_f[y0][:, x1] * wx
        bot = img_f[y1][:, x0] * (1 - wx) + img_f[y1][:, x1] * wx
        out = top * (1 - wy) + bot * wy
        return out.astype(img.dtype) if img.dtype == np.uint8 \
            else out.astype(np.float32)


class ImageCenterCrop(Preprocessing):
    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def transform(self, img):
        h, w = img.shape[:2]
        top = max(0, (h - self.h) // 2)
        left = max(0, (w - self.w) // 2)
        return img[top:top + self.h, left:left + self.w]


class ImageRandomCrop(_RandomOp):
    def __init__(self, crop_h: int, crop_w: int):
        super().__init__()
        self.h, self.w = int(crop_h), int(crop_w)

    def transform(self, img):
        rng = self.next_rng()
        h, w = img.shape[:2]
        top = int(rng.integers(0, max(h - self.h, 0) + 1))
        left = int(rng.integers(0, max(w - self.w, 0) + 1))
        return img[top:top + self.h, left:left + self.w]


class ImageHFlip(_RandomOp):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = float(p)

    def transform(self, img):
        if self.next_rng().random() < self.p:
            return img[:, ::-1]
        return img


class ImageBrightness(_RandomOp):
    """Additive brightness jitter in [delta_low, delta_high] (reference
    image/Brightness)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0):
        super().__init__()
        self.lo, self.hi = float(delta_low), float(delta_high)

    def transform(self, img):
        delta = self.next_rng().uniform(self.lo, self.hi)
        out = img.astype(np.float32) + delta
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageSaturation(_RandomOp):
    def __init__(self, lower: float = 0.5, upper: float = 1.5):
        super().__init__()
        self.lower, self.upper = float(lower), float(upper)

    def transform(self, img):
        s = self.next_rng().uniform(self.lower, self.upper)
        f = img.astype(np.float32)
        gray = f.mean(axis=-1, keepdims=True)
        out = gray + (f - gray) * s
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageHue(_RandomOp):
    """Hue rotation by [-delta, delta] degrees (reference image/Hue),
    approximated in RGB via the YIQ rotation matrix."""

    def __init__(self, delta: float = 18.0):
        super().__init__()
        self.delta = float(delta)

    def transform(self, img):
        theta = np.deg2rad(self.next_rng().uniform(-self.delta, self.delta))
        c, s = np.cos(theta), np.sin(theta)
        m = np.array([
            [0.299 + 0.701 * c + 0.168 * s,
             0.587 - 0.587 * c + 0.330 * s,
             0.114 - 0.114 * c - 0.497 * s],
            [0.299 - 0.299 * c - 0.328 * s,
             0.587 + 0.413 * c + 0.035 * s,
             0.114 - 0.114 * c + 0.292 * s],
            [0.299 - 0.300 * c + 1.250 * s,
             0.587 - 0.588 * c - 1.050 * s,
             0.114 + 0.886 * c - 0.203 * s],
        ], np.float32)
        out = img.astype(np.float32) @ m.T
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageExpand(_RandomOp):
    """Zoom-out expansion onto a mean-filled canvas (reference image/Expand,
    used by SSD augmentation)."""

    def __init__(self, max_expand_ratio: float = 4.0,
                 means=(123, 117, 104)):
        super().__init__()
        self.max_ratio = float(max_expand_ratio)
        self.means = np.asarray(means, np.float32)

    def transform(self, img):
        rng = self.next_rng()
        ratio = rng.uniform(1.0, self.max_ratio)
        h, w, c = img.shape
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.broadcast_to(
            self.means.astype(img.dtype), (nh, nw, c)
        ).copy()
        top = int(rng.integers(0, nh - h + 1))
        left = int(rng.integers(0, nw - w + 1))
        canvas[top:top + h, left:left + w] = img
        return canvas


class ImageChannelNormalize(Preprocessing):
    """(x - mean) / std per channel → float32 (reference
    image/ChannelNormalize); uses the C++ kernel when built."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0,
                 std_b=1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform(self, img):
        from analytics_zoo_tpu.native import lib

        if lib is not None and img.dtype == np.uint8:
            return lib.normalize_u8(img, self.mean, self.std)
        return (img.astype(np.float32) - self.mean) / self.std


class ImagePixelNormalizer(Preprocessing):
    """Subtract a per-pixel mean image (reference PixelNormalizer)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, img):
        return img.astype(np.float32) - self.means


class ImageMatToTensor(Preprocessing):
    """Reference MatToTensor: OpenCV mat → CHW tensor.  TPU-native layout
    is NHWC, so this is float32 conversion (+ optional layout swap for
    parity)."""

    def __init__(self, to_chw: bool = False):
        self.to_chw = to_chw

    def transform(self, img):
        out = np.asarray(img, np.float32)
        if self.to_chw:
            out = np.transpose(out, (2, 0, 1))
        return out


class ImageSetToSample(Preprocessing):
    """Attach the record as (feature, label) sample (reference
    ImageSetToSample)."""

    def transform(self, record):
        if isinstance(record, tuple):
            return record
        return (record, None)
