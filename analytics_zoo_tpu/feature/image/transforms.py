"""Image preprocessing ops — host-side, numpy/C++-backed, feeding infeed.

Reference: zoo/.../feature/image/ImageProcessing.scala + the ~25
OpenCV-backed ops under feature/image (resize, crop variants, flip, hue,
saturation, brightness, normalize, expand, jitter — SURVEY.md §2.1).  The
reference runs OpenCV via BigDL's JNI; here the per-record ops are numpy
(uint8 in, float32 out at the normalize boundary), with the normalize hot
loop optionally served by the C++ library
(analytics_zoo_tpu/native/zoonative.cpp).  Records are HWC uint8/float
numpy arrays; all ops are `Preprocessing` stages composing with ``>>``.

Geometric ops use seeded per-record RNG derived from a records counter so a
transformed FeatureSet remains reproducible/checkpointable (the reference's
OpenCV ops were non-deterministic across retries).
"""

from __future__ import annotations

import zlib

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing


def _rng_for(record_seed):
    return np.random.default_rng(record_seed)


_random_op_instances = 0


class _RandomOp(Preprocessing):
    """Base for randomized ops: derives an rng from a per-record counter.

    The seed mixes a stable hash of the class name with a process-wide
    instance index, so streams are (a) reproducible across process restarts
    (checkpoint resume replays the same augmentations) and (b) independent
    between instances of the same op class.
    """

    def __init__(self):
        global _random_op_instances
        _random_op_instances += 1
        self._instance = _random_op_instances
        self._class_seed = zlib.crc32(type(self).__name__.encode())
        self._counter = 0

    def next_rng(self):
        self._counter += 1
        return np.random.default_rng(
            (self._class_seed, self._instance, self._counter))


class ImageResize(Preprocessing):
    """Bilinear resize to (height, width) (reference image/Resize)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def transform(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        if (h, w) == (self.h, self.w):
            return img
        try:
            import cv2

            # The reference resizes through OpenCV (BigDL augmentation.
            # Resize); using cv2 here IS the oracle behavior.
            out = cv2.resize(img, (self.w, self.h),
                             interpolation=cv2.INTER_LINEAR)
            if out.ndim == 2 and img.ndim == 3:
                out = out[:, :, None]  # cv2 drops singleton channels
            return out
        except ImportError:
            pass
        # numpy fallback with OpenCV's half-pixel-center convention:
        # src = (dst + 0.5) * scale - 0.5
        ys = np.clip((np.arange(self.h) + 0.5) * h / self.h - 0.5, 0, h - 1)
        xs = np.clip((np.arange(self.w) + 0.5) * w / self.w - 0.5, 0, w - 1)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img_f = img.astype(np.float32)
        top = img_f[y0][:, x0] * (1 - wx) + img_f[y0][:, x1] * wx
        bot = img_f[y1][:, x0] * (1 - wx) + img_f[y1][:, x1] * wx
        out = top * (1 - wy) + bot * wy
        return np.clip(np.rint(out), 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out.astype(np.float32)


class ImageCenterCrop(Preprocessing):
    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def transform(self, img):
        h, w = img.shape[:2]
        top = max(0, (h - self.h) // 2)
        left = max(0, (w - self.w) // 2)
        return img[top:top + self.h, left:left + self.w]


class ImageRandomCrop(_RandomOp):
    def __init__(self, crop_h: int, crop_w: int):
        super().__init__()
        self.h, self.w = int(crop_h), int(crop_w)

    def transform(self, img):
        rng = self.next_rng()
        h, w = img.shape[:2]
        top = int(rng.integers(0, max(h - self.h, 0) + 1))
        left = int(rng.integers(0, max(w - self.w, 0) + 1))
        return img[top:top + self.h, left:left + self.w]


class ImageHFlip(_RandomOp):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = float(p)

    def transform(self, img):
        if self.next_rng().random() < self.p:
            return img[:, ::-1]
        return img


class ImageBrightness(_RandomOp):
    """Additive brightness jitter in [delta_low, delta_high] (reference
    image/Brightness)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0):
        super().__init__()
        self.lo, self.hi = float(delta_low), float(delta_high)

    def transform(self, img):
        delta = self.next_rng().uniform(self.lo, self.hi)
        out = img.astype(np.float32) + delta
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageSaturation(_RandomOp):
    def __init__(self, lower: float = 0.5, upper: float = 1.5):
        super().__init__()
        self.lower, self.upper = float(lower), float(upper)

    def transform(self, img):
        s = self.next_rng().uniform(self.lower, self.upper)
        f = img.astype(np.float32)
        gray = f.mean(axis=-1, keepdims=True)
        out = gray + (f - gray) * s
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageHue(_RandomOp):
    """Hue rotation by [-delta, delta] degrees (reference image/Hue),
    approximated in RGB via the YIQ rotation matrix."""

    def __init__(self, delta: float = 18.0):
        super().__init__()
        self.delta = float(delta)

    def transform(self, img):
        theta = np.deg2rad(self.next_rng().uniform(-self.delta, self.delta))
        c, s = np.cos(theta), np.sin(theta)
        m = np.array([
            [0.299 + 0.701 * c + 0.168 * s,
             0.587 - 0.587 * c + 0.330 * s,
             0.114 - 0.114 * c - 0.497 * s],
            [0.299 - 0.299 * c - 0.328 * s,
             0.587 + 0.413 * c + 0.035 * s,
             0.114 - 0.114 * c + 0.292 * s],
            [0.299 - 0.300 * c + 1.250 * s,
             0.587 - 0.588 * c - 1.050 * s,
             0.114 + 0.886 * c - 0.203 * s],
        ], np.float32)
        out = img.astype(np.float32) @ m.T
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ImageExpand(_RandomOp):
    """Zoom-out expansion onto a mean-filled canvas (reference image/Expand,
    used by SSD augmentation)."""

    def __init__(self, max_expand_ratio: float = 4.0,
                 means=(123, 117, 104)):
        super().__init__()
        self.max_ratio = float(max_expand_ratio)
        self.means = np.asarray(means, np.float32)

    def transform(self, img):
        rng = self.next_rng()
        ratio = rng.uniform(1.0, self.max_ratio)
        h, w, c = img.shape
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.broadcast_to(
            self.means.astype(img.dtype), (nh, nw, c)
        ).copy()
        top = int(rng.integers(0, nh - h + 1))
        left = int(rng.integers(0, nw - w + 1))
        canvas[top:top + h, left:left + w] = img
        return canvas


class ImageChannelNormalize(Preprocessing):
    """(x - mean) / std per channel → float32 (reference
    image/ChannelNormalize); uses the C++ kernel when built."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0,
                 std_b=1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform(self, img):
        from analytics_zoo_tpu.native import lib

        if lib is not None and img.dtype == np.uint8:
            return lib.normalize_u8(img, self.mean, self.std)
        return (img.astype(np.float32) - self.mean) / self.std


class ImagePixelNormalizer(Preprocessing):
    """Subtract a per-pixel mean image (reference PixelNormalizer)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, img):
        return img.astype(np.float32) - self.means


class ImageMatToTensor(Preprocessing):
    """Reference MatToTensor: OpenCV mat → CHW tensor.  TPU-native layout
    is NHWC, so this is float32 conversion (+ optional layout swap for
    parity)."""

    def __init__(self, to_chw: bool = False):
        self.to_chw = to_chw

    def transform(self, img):
        out = np.asarray(img, np.float32)
        if self.to_chw:
            out = np.transpose(out, (2, 0, 1))
        return out


class ImageSetToSample(Preprocessing):
    """Attach the record as (feature, label) sample (reference
    ImageSetToSample)."""

    def transform(self, record):
        if isinstance(record, tuple):
            return record
        return (record, None)


class ImageBytesToMat(Preprocessing):
    """Decode encoded image bytes (JPEG/PNG) to an HWC array (reference
    ImageBytesToMat.scala -> OpenCVMethod.fromImageBytes).  The reference
    decodes to BGR mats; default here is RGB (the rest of this stack is
    RGB) with ``order="BGR"`` for byte-exact reference parity."""

    def __init__(self, order: str = "RGB"):
        assert order in ("RGB", "BGR")
        self.order = order

    def transform(self, data):
        import cv2

        buf = np.frombuffer(bytes(data), np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)  # BGR
        if img is None:
            raise ValueError("undecodable image bytes")
        return img if self.order == "BGR" else img[:, :, ::-1]


class ImagePixelBytesToMat(Preprocessing):
    """Raw pixel bytes -> HWC uint8 array (reference
    ImagePixelBytesToMat.scala)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.shape = (int(height), int(width), int(channels))

    def transform(self, data):
        return np.frombuffer(bytes(data), np.uint8).reshape(self.shape)


class ImageChannelOrder(Preprocessing):
    """Swap RGB<->BGR (reference ImageChannelOrder.scala)."""

    def transform(self, img):
        return img[:, :, ::-1]


class ImageChannelScaledNormalizer(Preprocessing):
    """(x - per-channel mean) * scale, one scale for all channels
    (reference ImageChannelScaledNormalizer.scala)."""

    def __init__(self, mean_r: int, mean_g: int, mean_b: int, scale: float):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.scale = float(scale)

    def transform(self, img):
        return (img.astype(np.float32) - self.mean) * self.scale


class ImageFiller(Preprocessing):
    """Fill a (normalized-coordinate) region with a constant (reference
    ImageFiller.scala -> augmentation.Filler; used for occlusion-style
    augmentation)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: int = 255):
        self.x1, self.y1 = float(start_x), float(start_y)
        self.x2, self.y2 = float(end_x), float(end_y)
        self.value = value

    def transform(self, img):
        h, w = img.shape[:2]
        out = img.copy()
        out[int(self.y1 * h):int(self.y2 * h),
            int(self.x1 * w):int(self.x2 * w)] = self.value
        return out


class ImageFixedCrop(Preprocessing):
    """Crop a fixed region, in normalized or pixel coordinates (reference
    ImageFixedCrop.scala -> augmentation.FixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool, is_clip: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized
        self.is_clip = is_clip

    def transform(self, img):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        if self.is_clip:
            x1, x2 = max(0, x1), min(w, x2)
            y1, y2 = max(0, y1), min(h, y2)
        return img[int(y1):int(y2), int(x1):int(x2)]


class ImageMirror(Preprocessing):
    """Unconditional horizontal mirror (reference ImageMirror.scala ->
    BigDL augmentation.Mirror; the deterministic cousin of ImageHFlip)."""

    def transform(self, img):
        return img[:, ::-1]


class ImageRandomCropper(_RandomOp):
    """Random (or center) crop to a fixed size with optional random mirror
    (reference ImageRandomCropper.scala; the ImageNet training cropper)."""

    def __init__(self, crop_width: int, crop_height: int,
                 mirror: bool = True, cropper_method: str = "random"):
        super().__init__()
        self.w, self.h = int(crop_width), int(crop_height)
        self.mirror = mirror
        assert cropper_method in ("random", "center")
        self.method = cropper_method

    def transform(self, img):
        rng = self.next_rng()
        h, w = img.shape[:2]
        if h < self.h or w < self.w:
            # Fail here, not as a shape mismatch in np.stack three stages
            # later: the cropper contract is a fixed output size.
            raise ValueError(
                f"image {h}x{w} is smaller than crop "
                f"{self.h}x{self.w}; resize before ImageRandomCropper")
        if self.method == "random":
            top = int(rng.integers(0, h - self.h + 1))
            left = int(rng.integers(0, w - self.w + 1))
        else:
            top = (h - self.h) // 2
            left = (w - self.w) // 2
        out = img[top:top + self.h, left:left + self.w]
        if self.mirror and rng.random() < 0.5:
            out = out[:, ::-1]
        return out


class ImageRandomPreprocessing(_RandomOp):
    """Apply an inner preprocessing with probability ``prob`` (reference
    ImageRandomPreprocessing.scala; e.g. random expand in the SSD chain)."""

    def __init__(self, inner: Preprocessing, prob: float):
        super().__init__()
        self.inner = inner
        self.prob = float(prob)

    def transform(self, img):
        if self.next_rng().random() < self.prob:
            return self.inner.transform(img)
        return img


class ImageRandomResize(_RandomOp):
    """Resize the SHORT side to a random size in [min_size, max_size],
    preserving aspect ratio (reference ImageRandomResize.scala -> BigDL
    RandomResize; the Inception-style scale augmentation)."""

    def __init__(self, min_size: int, max_size: int):
        super().__init__()
        self.min_size, self.max_size = int(min_size), int(max_size)

    def transform(self, img):
        size = int(self.next_rng().integers(self.min_size,
                                            self.max_size + 1))
        h, w = img.shape[:2]
        if h < w:
            nh, nw = size, max(1, round(w * size / h))
        else:
            nh, nw = max(1, round(h * size / w)), size
        return ImageResize(nh, nw).transform(img)


class ImageMatToFloats(Preprocessing):
    """HWC array -> float32 (reference ImageMatToFloats.scala; layout stays
    NHWC — the TPU-native layout)."""

    def transform(self, img):
        return np.asarray(img, np.float32)


class ImageAspectScale(Preprocessing):
    """Scale so the short side is ``min_size`` without exceeding
    ``max_size`` on the long side (reference pipeline's aspect-preserving
    scale used by detection eval)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size, self.max_size = int(min_size), int(max_size)

    def transform(self, img):
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        scale = min(self.min_size / short, self.max_size / long)
        nh, nw = max(1, round(h * scale)), max(1, round(w * scale))
        return ImageResize(nh, nw).transform(img)


def assemble_crop_batch(images, out_h, out_w, rng=None, offsets=None,
                        flips=None, mirror=True, n_threads=None):
    """Pack variable-size HWC uint8 images into one (N, oh, ow, C) uint8
    batch with per-image random crop + horizontal flip — the host-side
    batch-assembly hot loop that feeds the per-chip infeed (SURVEY.md
    §2.3's justified native component).  Runs on C++ threads when the
    native library is built (``native.build_native()``), numpy otherwise;
    both paths are bit-identical.

    Either pass a seeded ``rng`` (offsets/flips are drawn from it — the
    deterministic-replay contract of the preprocessing chains) or pass
    explicit ``offsets`` (N, 2) and ``flips`` (N,).
    """
    import numpy as np

    from analytics_zoo_tpu import native

    n = len(images)
    need_rng = offsets is None or (flips is None and mirror)
    if need_rng and rng is None:
        raise ValueError(
            "pass a seeded rng (random crops/flips) or explicit "
            "offsets/flips — a hidden fixed seed would silently repeat "
            "the same augmentation every batch")
    if offsets is None:
        offsets = np.stack([
            [rng.integers(0, im.shape[0] - out_h + 1),
             rng.integers(0, im.shape[1] - out_w + 1)]
            for im in images
        ]).astype(np.int32)
    if flips is None:
        flips = (rng.random(n) < 0.5) if mirror else np.zeros(n, bool)
    offsets = np.asarray(offsets, np.int32).reshape(n, 2)
    flips = np.asarray(flips, bool).reshape(n)
    # validate BEFORE dispatch: the C++ path would otherwise read out of
    # bounds where the numpy path raises — same inputs must behave the same
    for i, im in enumerate(images):
        y0, x0 = int(offsets[i, 0]), int(offsets[i, 1])
        if y0 < 0 or x0 < 0 or y0 + out_h > im.shape[0] \
                or x0 + out_w > im.shape[1]:
            raise ValueError(
                f"image {i} ({im.shape[0]}x{im.shape[1]}): crop "
                f"({out_h}x{out_w} at {y0},{x0}) out of bounds")
    if native.lib is not None:
        return native.lib.assemble_batch(images, offsets,
                                         flips.astype(np.uint8),
                                         out_h, out_w, n_threads=n_threads)
    ch = images[0].shape[-1]
    out = np.empty((n, out_h, out_w, ch), np.uint8)
    for i, im in enumerate(images):
        y0, x0 = int(offsets[i, 0]), int(offsets[i, 1])
        crop = np.asarray(im, np.uint8)[y0:y0 + out_h, x0:x0 + out_w]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def resize_batch(batch, out_h, out_w, n_threads=None):
    """Bilinear-resize a (N, H, W, C) uint8 batch to (N, oh, ow, C) —
    the resize stage of the host preprocess chain (resize -> crop/flip ->
    normalize), on C++ threads when the native library is built, cv2
    otherwise.  Both use half-pixel-center sampling (cv2 INTER_LINEAR),
    agreeing to +-1 from uint8 rounding.
    """
    import numpy as np

    from analytics_zoo_tpu import native

    batch = np.ascontiguousarray(batch, np.uint8)
    if batch.ndim != 4:
        raise ValueError(f"expected (N, H, W, C) uint8, got {batch.shape}")
    if native.lib is not None:
        return native.lib.resize_bilinear(batch, out_h, out_w,
                                          n_threads=n_threads)
    import cv2

    out = np.empty((batch.shape[0], out_h, out_w, batch.shape[-1]),
                   np.uint8)
    for i, im in enumerate(batch):
        r = cv2.resize(im, (out_w, out_h), interpolation=cv2.INTER_LINEAR)
        out[i] = r if r.ndim == 3 else r[..., None]
    return out
