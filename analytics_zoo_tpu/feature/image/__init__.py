from analytics_zoo_tpu.feature.image.imageset import (  # noqa: F401
    ImageSet,
)
from analytics_zoo_tpu.feature.image.transforms import (  # noqa: F401
    ImageBrightness,
    ImageCenterCrop,
    ImageChannelNormalize,
    ImageExpand,
    ImageHFlip,
    ImageHue,
    ImageMatToTensor,
    ImagePixelNormalizer,
    ImageRandomCrop,
    ImageResize,
    ImageSaturation,
    ImageSetToSample,
)
