from analytics_zoo_tpu.feature.image.imageset import (  # noqa: F401
    ImageSet,
)
from analytics_zoo_tpu.feature.image.roi import (  # noqa: F401
    ImageColorJitter,
    ImageExpandRoi,
    ImageRandomSampler,
    ImageRoiChannelNormalize,
    ImageRoiHFlip,
    ImageRoiNormalize,
    ImageRoiResize,
    RoiFeatureSet,
    ssd_train_set,
    ssd_val_set,
)
from analytics_zoo_tpu.feature.image.transforms import (  # noqa: F401
    ImageBrightness,
    ImageCenterCrop,
    ImageChannelNormalize,
    ImageExpand,
    ImageHFlip,
    ImageHue,
    ImageMatToTensor,
    ImagePixelNormalizer,
    ImageRandomCrop,
    ImageResize,
    ImageSaturation,
    ImageSetToSample,
)
