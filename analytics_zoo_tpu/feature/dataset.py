"""FeatureSet — the distributed dataset abstraction.

TPU-native re-design of the reference's ``FeatureSet``
(zoo/.../feature/FeatureSet.scala):

- ``DRAMFeatureSet`` (FeatureSet.scala:411-421) → :class:`ArrayFeatureSet`:
  records cached in host RAM, feeding the per-chip infeed.
- ``DiskFeatureSet`` (FeatureSet.scala:332-409; train on 1/numSlice in DRAM,
  rest on disk) → :class:`ShardedFeatureSet`: file shards, a sliding slice
  resident per epoch.
- ``CachedDistributedFeatureSet.data`` endless random-offset shuffled
  iterator per partition (FeatureSet.scala:240-289) → seeded, *checkpointable*
  per-epoch shuffles: iterator state is (epoch, cursor, seed), so resume is
  exact — the reference's Spark iterators were not resumable, only retryable.
- PMEM tier (feature/pmem/*) → memory-mapped spool files on local SSD:
  ``FeatureSet.array(..., memory_type="PMEM")`` spills the arrays to
  ``.npy`` files and reads batches through the page cache, the TPU-VM
  analogue of Optane's beyond-DRAM byte-addressable capacity (see
  :meth:`ArrayFeatureSet.spill_to_mmap`).

The ``batch_size % num_model_replicas == 0`` contract follows the reference's
TFDataset (pyzoo .../net/tf_dataset.py:136-143); batches here are globally
sized and sharded over the mesh ``data`` axis by the caller
(``ZooContext.shard_batch``), XLA splitting them per-chip.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing

# "DRAM" | "DISK_<n>" | "PMEM".  PMEM (reference FeatureSet.scala's
# Optane tier: byte-addressable capacity beyond DRAM) maps on a TPU-VM to
# memory-mapped local-SSD files: the arrays spill to .npy spool files and
# batches read through the page cache, so resident memory is O(touched
# pages) and the OS evicts under pressure — datasets beyond RAM train
# with the same ArrayFeatureSet iterator contract (exact resume included).
MemoryType = str


def _as_list(x):
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return [np.asarray(a) for a in x]
    return [np.asarray(x)]


def _unwrap(xs):
    return xs[0] if xs is not None and len(xs) == 1 else xs


class FeatureSet:
    """Base: iterate shuffled minibatches with exact, resumable state."""

    # Optional jittable fn batch_dict -> batch_dict, applied ON DEVICE inside
    # the compiled train/eval step (see Estimator).  Lets the host ship
    # compact dtypes (uint8 images) and do normalization/augmentation on the
    # TPU where it fuses into the step — the host→device link, not the MXU,
    # is the scarce resource (SURVEY.md §7 hard-part #1).
    device_transform: Callable | None = None

    # ------------------------------------------------------------------
    # constructors (mirror FeatureSet.rdd / .array factories)
    # ------------------------------------------------------------------
    @staticmethod
    def of(x, y=None, sample_weight=None) -> "FeatureSet":
        if isinstance(x, FeatureSet):
            return x
        return ArrayFeatureSet(x, y, sample_weight)

    @staticmethod
    def array(x, y=None, sample_weight=None,
              memory_type: MemoryType = "DRAM",
              spool_dir: str | None = None) -> "FeatureSet":
        """Reference ``FeatureSet.array``/``FeatureSet.rdd``
        (FeatureSet.scala:423-466) — memory_type selects the tier:
        ``"PMEM"`` spills the arrays to memory-mapped spool files (see
        module note), ``"DRAM"`` keeps them resident.

        ``spool_dir``: where PMEM spool files land.  Point it at real
        local SSD when the default tempdir is tmpfs (RAM-backed) or a
        small partition — a tmpfs spool would hold the data in RAM
        twice, defeating the tier."""
        fs = ArrayFeatureSet(x, y, sample_weight)
        if str(memory_type).upper() == "PMEM":
            fs.spill_to_mmap(spool_dir)
        return fs

    @staticmethod
    def from_shards(paths: Sequence[str], memory_type: MemoryType = "DISK_4",
                    loader: Callable | None = None) -> "FeatureSet":
        n_slices = 1
        if memory_type.upper().startswith("DISK_"):
            n_slices = int(memory_type.split("_")[1])
        return ShardedFeatureSet(list(paths), n_slices=n_slices,
                                 loader=loader)

    @staticmethod
    def from_tfrecord(paths: Sequence[str], parse_fn: Callable | None = None,
                      memory_type: MemoryType = "DISK_4") -> "FeatureSet":
        """TFRecord shards -> FeatureSet (reference
        ``TFDataset.from_tfrecord_file``, pyzoo .../net/tf_dataset.py:456-501
        — no tensorflow needed here; see feature/tfrecord.py).

        ``parse_fn(feature_map) -> (x, y)`` maps one decoded
        tf.train.Example to arrays; default is the ImageNet JPEG+label
        layout (``imagenet_example_parser``)."""
        from analytics_zoo_tpu.feature.tfrecord import (
            count_tfrecord_records,
            imagenet_example_parser,
            tfrecord_loader,
        )

        parse = parse_fn or imagenet_example_parser()
        n_slices = 1
        if memory_type.upper().startswith("DISK_"):
            n_slices = int(memory_type.split("_")[1])
        return ShardedFeatureSet(
            list(paths), n_slices=n_slices, loader=tfrecord_loader(parse),
            sizer=count_tfrecord_records)

    # ------------------------------------------------------------------
    @property
    def num_samples(self) -> int:
        raise NotImplementedError

    def transform(self, preprocessing: Preprocessing) -> "FeatureSet":
        """Attach a per-record transform (reference ``-> transformer``,
        FeatureSet.scala:82-84)."""
        return TransformedFeatureSet(self, preprocessing)

    def transform_on_device(self, fn: Callable) -> "FeatureSet":
        """Attach a jittable per-batch transform run inside the compiled
        step (composes with any transform already attached)."""
        prev = self.device_transform
        if prev is None:
            self.device_transform = fn
        else:
            self.device_transform = lambda b, _p=prev, _f=fn: _f(_p(b))
        return self

    def prefetch(self, depth: int = 4, workers: int = 2) -> "FeatureSet":
        """Run batch production through the parallel host data plane
        (feature/prefetch.py): shard loading, decode, host transforms and
        batch assembly move off the consumer thread onto ``workers`` pool
        threads behind a ``depth``-bounded queue, with ORDERED delivery —
        the stream stays byte-identical to the serial path for the same
        seed/epoch/start_batch."""
        from analytics_zoo_tpu.feature.prefetch import PrefetchFeatureSet
        return PrefetchFeatureSet(self, depth=depth, workers=workers)

    def batches(self, batch_size: int, shuffle: bool = True,
                seed: int = 0, epoch: int = 0, drop_last: bool = True,
                start_batch: int = 0,
                pad_to_batch: int | None = None,
                process_shard: tuple[int, int] | None = None) -> Iterator[dict]:
        """Yield dict batches {"x": ..., "y": ..., "w": ...}.

        One pass = one epoch; shuffling is a seeded permutation of
        (seed, epoch) so any (epoch, batch_index) position is reproducible —
        the checkpointable re-design of the reference's endless random-offset
        iterator (FeatureSet.scala:240-289).

        ``process_shard=(process_index, process_count)`` (multi-host):
        every process iterates the SAME
        global batch schedule (same seed ⇒ same permutation) but
        materializes only its slice of each batch's rows; the caller
        reassembles the global array via
        ``jax.make_array_from_process_local_data`` (ZooContext.shard_batch).
        Scalar entries (``n_valid``) stay global.
        """
        raise NotImplementedError

    def steps_per_epoch(self, batch_size: int, drop_last: bool = True) -> int:
        n = self.num_samples
        return n // batch_size if drop_last else -(-n // batch_size)


def _batch_from_arrays(xs, ys, ws, idx, pad_to=None, process_shard=None):
    n_valid = len(idx)
    if pad_to is not None and n_valid % pad_to != 0:
        # Padding happens at the index level (repeat the last row) so a
        # process slice below materializes only local rows.
        pad = pad_to - n_valid % pad_to
        idx = np.concatenate([idx, np.repeat(idx[-1:], pad, axis=0)])
    if process_shard is not None:
        from analytics_zoo_tpu.parallel.multihost import (
            process_local_batch_slice,
        )
        idx = idx[process_local_batch_slice(len(idx), process_shard)]
    take = lambda arrs: _unwrap([a[idx] for a in arrs]) \
        if arrs is not None else None
    batch = {"x": take(xs)}
    if ys is not None:
        batch["y"] = take(ys)
    if ws is not None:
        batch["w"] = take(ws)
    if pad_to is not None:
        # Padded rows are marked via n_valid (a GLOBAL count) so evaluation
        # masks them out of loss/metric denominators.
        batch["n_valid"] = np.asarray(n_valid, np.int32)
    return batch


def _host_nbytes(d) -> int:
    """Host bytes of a dict of arrays / array lists (0 for other shapes)
    — the ONE accounting used for both batch and shard sizes feeding the
    autotune RAM-budget estimate (feature/autotune.py)."""
    if not isinstance(d, dict):
        return 0
    return int(sum(
        getattr(a, "nbytes", 0)
        for v in d.values()
        for a in (v if isinstance(v, (list, tuple)) else (v,))))


def _slice_batch_rows(batch, process_shard):
    """Row-slice an already-materialized global batch (scalars untouched)."""
    if process_shard is None:
        return batch
    from analytics_zoo_tpu.parallel.multihost import process_local_batch_slice

    def rows(v):
        return len(v[0]) if isinstance(v, list) else len(v)
    sl = process_local_batch_slice(rows(batch["x"]), process_shard)
    out = {}
    for k, v in batch.items():
        if k == "n_valid" or np.ndim(v) == 0:
            out[k] = v
        elif isinstance(v, list):
            out[k] = [a[sl] for a in v]
        else:
            out[k] = v[sl]
    return out


class ArrayFeatureSet(FeatureSet):
    """DRAM tier (reference DRAMFeatureSet, FeatureSet.scala:411-421)."""

    def __init__(self, x, y=None, sample_weight=None):
        self.xs = _as_list(x)
        self.ys = _as_list(y)
        self.ws = _as_list(sample_weight)
        n = len(self.xs[0])
        for a in self.xs + (self.ys or []) + (self.ws or []):
            assert len(a) == n, "all arrays must share leading dim"
        self._n = n

    @property
    def num_samples(self) -> int:
        return self._n

    def spill_to_mmap(self, spool_dir: str | None = None):
        """The PMEM tier: rewrite every array as an ``.npy`` spool file
        and reopen it memory-mapped read-only.  Batch fancy-indexing then
        touches only the needed pages; the page cache is the fast tier
        and the OS reclaims it under pressure (the role persistent
        memory played for the reference's DRAMFeatureSet variant)."""
        import tempfile

        self._spool = tempfile.TemporaryDirectory(
            prefix="zoo_pmem_", dir=spool_dir)  # kept: deletes on GC

        def mm(arrs, tag):
            if arrs is None:
                return None
            out = []
            for i, a in enumerate(arrs):
                path = os.path.join(self._spool.name, f"{tag}{i}.npy")
                np.save(path, a)
                out.append(np.load(path, mmap_mode="r"))
            return out

        self.xs = mm(self.xs, "x")
        self.ys = mm(self.ys, "y")
        self.ws = mm(self.ws, "w")
        return self

    def batches(self, batch_size, shuffle=True, seed=0, epoch=0,
                drop_last=True, start_batch=0, pad_to_batch=None,
                process_shard=None):
        n = self._n
        if shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([seed, epoch])
            ).permutation(n)
        else:
            order = np.arange(n)
        n_batches = n // batch_size if drop_last else -(-n // batch_size)
        for b in range(start_batch, n_batches):
            idx = order[b * batch_size:(b + 1) * batch_size]
            yield _batch_from_arrays(self.xs, self.ys, self.ws, idx,
                                     pad_to_batch, process_shard)


class ShardedFeatureSet(FeatureSet):
    """Disk tier with a resident slice (reference DiskFeatureSet,
    FeatureSet.scala:332-409: trains on 1/numSlice of data in DRAM while the
    rest stays on disk; the resident slice advances every epoch).

    ``paths`` are ``.npz`` files with arrays ``x`` (and optionally ``y``,
    ``w``), or anything a custom ``loader(path) -> dict`` understands.
    """

    def __init__(self, paths: Sequence[str], n_slices: int = 4,
                 loader: Callable | None = None,
                 sizer: Callable | None = None):
        assert paths, "no shards given"
        self.paths = list(paths)
        self.n_slices = max(1, min(int(n_slices), len(self.paths)))
        self._default_format = loader is None
        self.loader = loader or self._default_loader
        # sizer(path) -> record count without materializing the shard
        # (npz: zip headers; tfrecord: framing walk).  Without one, a custom
        # loader pays a full load per shard the first time sizes are needed.
        self.sizer = sizer
        self._cache: dict[str, dict] = {}
        self._sizes: list[int] | None = None
        # shard read-ahead (feature/prefetch.py): when a pool is set,
        # batches() submits loader(path_{k+1}) while slice k is consumed,
        # so _load() finds the next slice already (being) materialized
        # instead of stalling the feeder cold on every slice advance
        self._ra_pool = None
        self._ra_futures: dict[str, Any] = {}
        # how many not-yet-resident shards may load ahead (autotune's
        # read-ahead knob; plain int store — written by the controller
        # thread, read by the producer, no torn state possible)
        self._ra_ahead = 1
        # host bytes of the last loaded shard (autotune RAM estimate:
        # each read-ahead slot transiently holds ~one shard)
        self._last_shard_nbytes = 0

    @staticmethod
    def _default_loader(path: str) -> dict:
        data = np.load(path, allow_pickle=False)
        return {k: data[k] for k in data.files}

    @staticmethod
    def _npz_first_dim(path: str) -> int:
        """Read the leading dim of ``x`` from the npz member header — no
        array data is read, so sizing a shard costs ~1 KB of IO.

        Handles npy header versions (1,0), (2,0) AND (3,0) (numpy emits
        3.0 for long utf-8 field names); an unparseable header falls back
        to a full member load rather than raising — sizing must never be
        the thing that kills an epoch."""
        import zipfile

        from numpy.lib import format as npformat

        try:
            with zipfile.ZipFile(path) as z:
                with z.open("x.npy") as f:
                    version = npformat.read_magic(f)
                    if version == (1, 0):
                        shape, _, _ = npformat.read_array_header_1_0(f)
                    elif version == (2, 0):
                        shape, _, _ = npformat.read_array_header_2_0(f)
                    else:
                        # (3,0) shares the 2.0 layout with a utf-8 header;
                        # numpy's generic reader knows every version it
                        # can itself write
                        shape, _, _ = npformat._read_array_header(
                            f, version)
                    return int(shape[0])
        except Exception:
            return len(np.load(path, allow_pickle=False)["x"])

    def _shard_sizes(self):
        if self._sizes is None:
            if self.sizer is not None:
                self._sizes = [int(self.sizer(p)) for p in self.paths]
            elif self._default_format:
                self._sizes = [self._npz_first_dim(p) for p in self.paths]
            else:
                # Custom loader without a sizer: sizes require loading once
                # (through the resident cache).
                self._sizes = [len(_as_list(self._load(p)["x"])[0])
                               for p in self.paths]
        return self._sizes

    def set_read_ahead(self, pool, ahead: int | None = None) -> None:
        """Enable (an executor) / disable (None) shard read-ahead.

        With a pool set, up to ``ahead`` (default 1) not-yet-resident
        shards may be loading in the background — transiently
        budget+ahead slices of memory.  Managed by
        :class:`~analytics_zoo_tpu.feature.prefetch.PrefetchFeatureSet`
        around each iteration; usable standalone with any executor.
        Disabling (``pool=None``) also resets the read-ahead count to
        the default 1, so a count tuned by one run's autotune controller
        never silently leaks into a later non-autotuned run's memory
        footprint — pass ``ahead=`` to pin a custom count."""
        self._ra_pool = pool
        if ahead is not None:
            self.set_read_ahead_count(ahead)
        if pool is None:
            self._ra_futures = {}
            if ahead is None:
                self._ra_ahead = 1

    def set_read_ahead_count(self, ahead: int) -> None:
        """How many shards ahead of the cursor may load concurrently
        (the autotune read-ahead knob — each extra slot trades ~one
        shard of host RAM for one fewer cold slice advance)."""
        if ahead < 1:
            raise ValueError(f"read-ahead count must be >= 1, got {ahead}")
        self._ra_ahead = int(ahead)

    @property
    def last_shard_nbytes(self) -> int:
        """Host bytes of the most recently loaded shard (0 before any
        load) — the autotune RAM-budget estimator's per-slot cost."""
        return self._last_shard_nbytes

    def _read_ahead(self, path):
        if self._ra_pool is None or path in self._cache \
                or path in self._ra_futures:
            return
        try:
            self._ra_futures[path] = self._ra_pool.submit(self.loader, path)
        except RuntimeError:
            pass  # pool shutting down mid-epoch: fall back to sync loads

    def _load(self, path):
        if path not in self._cache:
            # keep at most ceil(len/n_slices) shards resident
            budget = -(-len(self.paths) // self.n_slices)
            while len(self._cache) >= max(budget, 1):
                self._cache.pop(next(iter(self._cache)))
            fut = self._ra_futures.pop(path, None)
            data = fut.result() if fut is not None else self.loader(path)
            self._cache[path] = data
            if isinstance(data, dict):
                self._last_shard_nbytes = _host_nbytes(data)
        return self._cache[path]

    @property
    def num_samples(self) -> int:
        return sum(self._shard_sizes())

    def batches(self, batch_size, shuffle=True, seed=0, epoch=0,
                drop_last=True, start_batch=0, pad_to_batch=None,
                process_shard=None):
        # Shard iteration state is global (every host walks the same shard
        # schedule); only the materialized rows are process-sliced at yield.
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        shard_order = (rng.permutation(len(self.paths)) if shuffle
                       else np.arange(len(self.paths)))
        def concat(a, b_):
            if isinstance(a, list):
                return [concat(x1, x2) for x1, x2 in zip(a, b_)]
            return np.concatenate([a, b_], axis=0)

        def blen(batch):
            v = batch["x"]
            return len(v[0]) if isinstance(v, list) else len(v)

        # Resume (start_batch > 0) is O(1) in shard IO: shards no emitted
        # batch touches are skipped arithmetically — the RNG stream is kept
        # aligned by drawing (and discarding) their permutations, and their
        # contribution to a partially-assembled batch is tracked as a
        # row COUNT (``leftover`` as int), never materialized.  Only shards
        # overlapping stream rows >= start_batch*batch_size are loaded.
        # (Round-2 verdict Weak #4: the old path re-loaded and re-iterated
        # every shard from position 0.)
        stream_start = start_batch * batch_size
        sizes = self._shard_sizes() if start_batch > 0 else None
        b = 0
        cum = 0
        leftover = None  # None | dict (real rows) | int (virtual row count)
        for j, si in enumerate(shard_order):
            if sizes is not None and cum + sizes[si] <= stream_start:
                n = sizes[si]
                if shuffle:
                    rng.permutation(n)  # keep the RNG stream aligned
                cum += n
                b = cum // batch_size
                rem = cum % batch_size
                leftover = rem if rem else None
                continue
            data = self._load(self.paths[si])
            # overlap the next slice loads with this slice's consumption
            # (no-op without a read-ahead pool); every shard after a
            # loaded one is itself loaded, so the speculation can never
            # be wasted work.  _ra_ahead (autotune's read-ahead knob)
            # bounds how many load ahead concurrently.
            for jn in range(j + 1, min(j + 1 + self._ra_ahead,
                                       len(shard_order))):
                self._read_ahead(self.paths[shard_order[jn]])
            xs = _as_list(data["x"])
            ys = _as_list(data.get("y"))
            ws = _as_list(data.get("w"))
            n = len(xs[0])
            cum += n
            order = rng.permutation(n) if shuffle else np.arange(n)
            pos = 0
            if isinstance(leftover, int):
                # Rows completing a batch of index < start_batch (guaranteed
                # by the skip condition): consume without materializing.
                # This shard was loaded because cum_before + n > stream_start
                # >= (b+1)*batch_size, so it always holds the `need` rows.
                need = batch_size - leftover
                assert need <= n, (need, n, b, start_batch)
                pos = need
                b += 1
                leftover = None
            elif leftover is not None:
                need = batch_size - blen(leftover)
                idx = order[:need]
                fresh = _batch_from_arrays(xs, ys, ws, idx)
                merged = {k: concat(leftover[k], fresh[k]) for k in leftover}
                pos = need
                if blen(merged) == batch_size:
                    if b >= start_batch:
                        yield _slice_batch_rows(merged, process_shard)
                    b += 1
                    leftover = None
                else:
                    leftover = merged
                    continue
            while pos + batch_size <= n:
                idx = order[pos:pos + batch_size]
                if b >= start_batch:
                    yield _batch_from_arrays(xs, ys, ws, idx,
                                             process_shard=process_shard)
                b += 1
                pos += batch_size
            if pos < n:
                leftover = _batch_from_arrays(xs, ys, ws, order[pos:])
        if isinstance(leftover, dict) and not drop_last:
            if pad_to_batch is not None:
                n_valid = blen(leftover)
                pad = (-n_valid) % pad_to_batch

                def pad_fn(v):
                    if isinstance(v, list):
                        return [pad_fn(a) for a in v]
                    return np.concatenate(
                        [v, np.repeat(v[-1:], pad, axis=0)], axis=0
                    ) if pad else v

                leftover = {k: pad_fn(v) for k, v in leftover.items()}
                leftover["n_valid"] = np.asarray(n_valid, np.int32)
            yield _slice_batch_rows(leftover, process_shard)


def _preprocess_batch(preprocessing: Preprocessing, batch: dict) -> dict:
    """Apply a per-record transform to one assembled batch.

    Shared by the serial TransformedFeatureSet path and the prefetch
    pipeline's pooled map stage (feature/prefetch.py) — one
    implementation is what makes the two streams byte-identical."""
    xs = batch["x"]
    single = not isinstance(xs, list)
    records = xs if single else list(zip(*xs))
    out = [preprocessing(r) for r in records]
    batch = dict(batch)
    batch["x"] = np.stack(out) if single else [
        np.stack(col) for col in zip(*out)
    ]
    return batch


class TransformedFeatureSet(FeatureSet):
    """Per-record preprocessing applied at batch-assembly time."""

    def __init__(self, base: FeatureSet, preprocessing: Preprocessing):
        self.base = base
        self.preprocessing = preprocessing

    @property
    def device_transform(self):
        """Delegates to the base so transforms attached to either level —
        even after this wrapper was built — are seen by the estimator."""
        return self.base.device_transform

    @device_transform.setter
    def device_transform(self, fn):
        self.base.device_transform = fn

    @property
    def num_samples(self):
        return self.base.num_samples

    def batches(self, *args, **kwargs):
        for batch in self.base.batches(*args, **kwargs):
            yield _preprocess_batch(self.preprocessing, batch)
