"""Pooled, batched inference runner.

Reference: ``InferenceModel`` (pipeline/inference/InferenceModel.scala:81-657)
— the ``doLoad*`` family loads a model into a pool of ``supportedConcurrentNum``
copies held in a LinkedBlockingQueue (:31-73); ``doPredict`` (:623-657) takes a
copy from the queue, runs it, and offers it back.  The Java POJO surface is
AbstractInferenceModel.java.

TPU-native re-design: one jit-compiled XLA executable is pure and reentrant,
so there are no model copies — ``concurrent_num`` instead bounds in-flight
predict calls with a semaphore (device queue depth), and a per-input-shape
**AOT compile cache** plays the role of OpenVINO's offline model conversion
(OpenVinoInferenceSupportive.scala): shapes are bucketed to powers of two so
a bounded set of executables serves arbitrary batch sizes.
"""

from __future__ import annotations

import threading
from typing import Sequence

import jax
import numpy as np

from analytics_zoo_tpu.metrics import get_registry, span
from analytics_zoo_tpu.pipeline.inference.quantize import (
    dequantize_params,
    quantize_params,
)


def _bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n (capped), so recompiles are O(log max)."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return min(b, max_batch)


class InferenceModel:
    """Load-once, predict-many inference engine.

    ``concurrent_num`` mirrors the reference pool size
    (InferenceModel.scala:31-73).  ``predict`` accepts a single ndarray or a
    list (multi-input models) and handles batching/padding internally.
    """

    def __init__(self, concurrent_num: int = 4, max_batch: int = 1024):
        self.concurrent_num = int(concurrent_num)
        self.max_batch = int(max_batch)
        self._sem = threading.Semaphore(self.concurrent_num)
        self._net = None
        self._params = None
        self._state = None
        self._compiled = {}  # guarded-by: _lock -- shape-key -> executable
        self._lock = threading.Lock()
        self._quantized = False
        self._int8_model = None
        self._bf16 = False
        # Telemetry (metrics/): compile count + execution latency per
        # batch bucket — the bucketed-compile-cache health signals (a
        # growing compile count means shape churn is defeating the cache)
        reg = get_registry()
        self._m_compiles = reg.counter(
            "zoo_inference_compiles_total",
            "XLA compiles by input shape bucket", ("bucket",))
        self._m_latency = reg.histogram(
            "zoo_inference_predict_seconds",
            "executable run time per micro-batch", ("bucket",))
        self._m_records = reg.counter(
            "zoo_inference_records_total", "records predicted")

    # ------------------------------------------------------------------
    # doLoad* family (InferenceModel.scala:81-657)
    # ------------------------------------------------------------------
    def load(self, path: str) -> "InferenceModel":
        """Load a saved KerasNet / ZooModel (reference ``doLoadBigDL``)."""
        from analytics_zoo_tpu.models.common import ZooModel
        from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet

        obj = ZooModel.load_model(path)
        net = obj.model if isinstance(obj, ZooModel) else obj
        if not isinstance(net, KerasNet):
            raise ValueError(f"{path} does not contain a KerasNet")
        return self.from_keras_net(net)

    def from_keras_net(self, net) -> "InferenceModel":
        """Wrap an in-memory model (reference ``doLoad`` from bytes)."""
        net.build_params()
        self._net = net
        self._params = net.params
        self._state = net.state
        with self._lock:
            self._compiled = {}
        self._quantized = False
        self._int8_model = None
        self._bf16 = False
        return self

    def load_torch(self, module, input_shape) -> "InferenceModel":
        """Run a (CPU) torch module behind the same predict surface
        (reference ``doLoadPyTorch`` → TorchNet.scala:39-156).  The module is
        executed on host — the escape hatch for models not yet ported; jax
        models should use :meth:`load`/:meth:`from_keras_net`."""
        import torch

        module.eval()
        self._torch = (module, torch)
        self._net = None
        with self._lock:
            self._compiled = {}
        return self

    def optimize(self, precision: str = "int8",
                 calibration_data=None) -> "InferenceModel":
        """Offline optimization pass (the OpenVINO-conversion role,
        InferenceModel.scala doLoadOpenVINO* + int8 calibration).

        ``int8``: weight-only per-channel quantization (HBM traffic ~4x
        lower); with ``calibration_data`` (representative inputs, the
        reference's calibration dataset), activations are calibrated too
        and Dense/Conv layers execute int8 x int8 -> int32 on the MXU;
        ``bf16``: cast weights to bfloat16 (MXU-native).
        """
        if self._net is None:
            raise RuntimeError("load a model first")
        if precision not in ("int8", "bf16"):
            # validate BEFORE mutating: a bad precision must not leave the
            # model half-reconfigured with stale executables
            raise ValueError(f"unknown precision {precision!r}")
        self._int8_model = None  # every optimize() choice starts clean
        self._bf16 = False
        if precision == "int8" and calibration_data is not None:
            from analytics_zoo_tpu.pipeline.inference.quantize import (
                quantize_model,
            )

            self._int8_model = quantize_model(self._net, calibration_data)
            self._params = self._int8_model.qparams
            self._quantized = True
        elif precision == "int8":
            self._params = quantize_params(self._net.params)
            self._quantized = True
        elif precision == "bf16":
            import jax.numpy as jnp

            self._params = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
                self._net.params,
            )
            self._quantized = False
            self._bf16 = True
        with self._lock:
            self._compiled = {}
        return self

    @staticmethod
    def enable_persistent_compile_cache(cache_dir: str) -> None:
        """Persistent XLA compile cache on disk — the moral equivalent of
        OpenVINO's saved IR: second process start skips compilation.
        Delegates to the shared compile plane
        (:mod:`analytics_zoo_tpu.common.compile_cache`), same as
        ``ZOO_COMPILE_CACHE``."""
        from analytics_zoo_tpu.common.compile_cache import (
            maybe_enable_persistent_cache,
        )
        maybe_enable_persistent_cache(cache_dir)

    # ------------------------------------------------------------------
    # compile cache
    # ------------------------------------------------------------------
    def _forward_fn(self):
        import jax.numpy as jnp

        net, quantized = self._net, self._quantized
        calibrated = getattr(self, "_int8_model", None) is not None
        bf16 = getattr(self, "_bf16", False)

        def fwd(params, state, xs):
            # calibrated int8: wrapped layers read their int8 kernels from
            # the installed apply hooks (params supplies only float leaves
            # like biases), so no dequantization pass
            if quantized and not calibrated:
                params = dequantize_params(params)
            if bf16:
                # weights are bf16: inputs must match (conv/dot require
                # uniform dtypes); results return in f32 for callers
                xs = [x.astype(jnp.bfloat16)
                      if jnp.issubdtype(x.dtype, jnp.floating) else x
                      for x in xs]
            x = xs[0] if len(xs) == 1 else list(xs)
            out, _ = net.forward(params, x, state=state, training=False)
            if bf16:
                out = jax.tree_util.tree_map(
                    lambda o: o.astype(jnp.float32), out)
            return out

        return fwd

    def _get_compiled(self, xs: Sequence[np.ndarray]):
        key = tuple((a.shape, str(a.dtype)) for a in xs)
        exe = self._compiled.get(key)
        if exe is None:
            with self._lock:
                exe = self._compiled.get(key)
                if exe is None:
                    # AOT: lower + compile now, store the executable.  For
                    # calibrated int8 the apply hooks are installed only
                    # while tracing; the executable bakes in the int8
                    # path.  Every compile holds the global HOOK_LOCK so
                    # no trace can observe another model's hooks (layer
                    # .apply is shared net-wide state).
                    from analytics_zoo_tpu.pipeline.inference.quantize \
                        import HOOK_LOCK

                    from analytics_zoo_tpu.common.compile_cache import (
                        maybe_enable_persistent_cache,
                        timed_compile,
                    )

                    int8 = getattr(self, "_int8_model", None)
                    ctx = int8.installed() if int8 is not None \
                        else HOOK_LOCK
                    bucket = str(xs[0].shape[0]) if np.ndim(xs[0]) else "0"
                    # ZOO_COMPILE_CACHE: an already-served bucket shape
                    # compiles as a persistent-cache hit on restart
                    maybe_enable_persistent_cache()
                    # ISSUE 20: stamp the serving context (pad bucket,
                    # precision-qualified plan, device footprint) so the
                    # predict-labelled zoo-hlo-report rows are joinable
                    # cost-model training examples like train rows
                    precision = ("int8" if self._quantized
                                 else "bf16" if self._bf16 else "f32")
                    meta = {
                        "bucket": int(bucket) if bucket.isdigit() else None,
                        "plan": f"serving+{precision}",
                        "mesh_shape": {"replica": jax.local_device_count()},
                    }
                    with ctx, span("zoo.inference.compile",
                                   args={"bucket": bucket}):
                        exe = timed_compile(
                            jax.jit(self._forward_fn())
                            .lower(self._params, self._state, list(xs)),
                            f"inference_b{bucket}",
                            meta=meta,
                        )
                    self._m_compiles.labels(bucket=bucket).inc()
                    self._compiled[key] = exe
        return exe

    def warmup(self, input_shapes, dtype=np.float32,
               batch_sizes=(1,)) -> None:
        """Pre-compile executables for the given bucket shapes
        (offline-conversion step; avoids first-request latency).  Batch
        sizes are rounded up to the power-of-two buckets predict actually
        requests.  Goes through the compile plane
        (``common/compile_cache.py``): each ``.lower().compile()`` is
        timed into ``zoo_compile_seconds{label=inference_b<bucket>}``,
        and with ``ZOO_COMPILE_CACHE`` set a restarted server warms from
        disk instead of XLA."""
        shapes = input_shapes
        if shapes and not isinstance(shapes[0], (list, tuple)):
            shapes = [shapes]
        for b in {_bucket(int(b), self.max_batch) for b in batch_sizes}:
            xs = [np.zeros((b,) + tuple(s), dtype) for s in shapes]
            self._get_compiled(xs)

    # ------------------------------------------------------------------
    # doPredict (InferenceModel.scala:623-657)
    # ------------------------------------------------------------------
    def predict(self, inputs, batch_size: int | None = None) -> np.ndarray:
        """Batched inference.  Pads each micro-batch to a power-of-two bucket
        (static shapes for XLA), bounded by the concurrency semaphore."""
        if getattr(self, "_torch", None) is not None and self._net is None:
            module, torch = self._torch
            xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            xs = [np.asarray(a) for a in xs]
            n = xs[0].shape[0]
            step = min(batch_size or max(n, 1), self.max_batch)
            outs = []
            for lo in range(0, n, step):
                args = [torch.as_tensor(a[lo:lo + step]) for a in xs]
                with self._sem, torch.no_grad():
                    outs.append(module(*args).numpy())
            if not outs:
                with torch.no_grad():
                    probe = module(*[torch.as_tensor(a[:1]) for a in
                                     [np.zeros((1,) + x.shape[1:], x.dtype)
                                      for x in xs]])
                return np.zeros((0,) + tuple(probe.shape[1:]),
                                probe.numpy().dtype)
            return np.concatenate(outs, axis=0)
        if self._net is None:
            raise RuntimeError("no model loaded")

        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(a) for a in xs]
        n = xs[0].shape[0]
        if n == 0:
            # run a padded singleton through the bucket-1 executable just to
            # learn the output shape, then return it empty
            dummy = [np.zeros((1,) + a.shape[1:], a.dtype) for a in xs]
            exe = self._get_compiled(dummy)
            out = exe(self._params, self._state, dummy)
            if isinstance(out, (list, tuple)):
                return [np.zeros((0,) + tuple(np.asarray(o).shape[1:]),
                                 np.asarray(o).dtype) for o in out]
            return np.zeros((0,) + tuple(np.asarray(out).shape[1:]),
                            np.asarray(out).dtype)
        step = min(batch_size or n, self.max_batch)
        outs = []
        for lo in range(0, n, step):
            chunk = [a[lo:lo + step] for a in xs]
            m = chunk[0].shape[0]
            b = _bucket(m, self.max_batch)
            if b != m:
                chunk = [
                    np.concatenate(
                        [a, np.zeros((b - m,) + a.shape[1:], a.dtype)]
                    )
                    for a in chunk
                ]
            exe = self._get_compiled(chunk)
            with self._sem, self._m_latency.labels(bucket=str(b)).time():
                out = exe(self._params, self._state, chunk)
                # materialize inside the semaphore so concurrent_num truly
                # bounds in-flight device work (dispatch is async)
                if isinstance(out, (list, tuple)):
                    out = [np.asarray(o)[:m] for o in out]
                else:
                    out = np.asarray(out)[:m]
            outs.append(out)
            self._m_records.inc(m)
        if isinstance(outs[0], list):
            return [np.concatenate([o[i] for o in outs])
                    for i in range(len(outs[0]))]
        return np.concatenate(outs, axis=0)

    # camelCase aliases matching the reference Java/Scala POJO surface
    doPredict = predict
    doLoad = load


class AbstractInferenceModel(InferenceModel):
    """Java-POJO-style subclassable surface
    (reference AbstractInferenceModel.java)."""
