"""Weight-only int8 quantization for inference.

Reference role: OpenVINO int8 calibration
(InferenceModel.scala ``doLoadOpenVINOInt8`` family;
OpenVinoInferenceSupportive.scala:33-61) with the whitepaper claim of 4x
model-size reduction at <=0.1% accuracy drop (docs/docs/wp-bigdl.md:192).

TPU-native design: per-output-channel symmetric int8 quantization of the
*parameter pytree*; activations stay bf16/f32.  Dequantization happens
on-device right before the matmul/conv, which XLA fuses into the consumer, so
HBM traffic for weights drops ~4x — the same bandwidth win the reference gets
from VNNI int8 — while the MXU still sees bf16 operands.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np


class QuantizedTensor:
    """int8 values + per-channel float scale; a pytree leaf pair."""

    def __init__(self, values, scale, axis: int):
        self.values = values          # int8, original shape
        self.scale = scale            # f32, broadcastable to values
        self.axis = axis

    def dequantize(self, dtype=jnp.float32):
        return self.values.astype(dtype) * self.scale.astype(dtype)

    @property
    def shape(self):
        return self.values.shape

    def tree_flatten(self):
        return (self.values, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        return cls(children[0], children[1], axis)


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    QuantizedTensor.tree_flatten,
    QuantizedTensor.tree_unflatten,
)


def _quantize_array(a, axis: int) -> QuantizedTensor:
    a = jnp.asarray(a)
    reduce_axes = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
    amax = jnp.max(jnp.abs(a), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(a / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale, axis % a.ndim)


def quantize_params(params, min_size: int = 1024):
    """Quantize every large (>= min_size elements, ndim >= 2) weight to int8.

    Channel axis = last dim (dense kernels (in, out) and conv kernels
    (..., in, out) both store output channels last in this framework).
    Small tensors (biases, norms) stay in full precision — matching the
    reference's calibration behavior of only quantizing conv/FC weights.
    """
    def q(leaf):
        arr = jnp.asarray(leaf)
        if arr.ndim >= 2 and arr.size >= min_size and jnp.issubdtype(
                arr.dtype, jnp.floating):
            return _quantize_array(arr, axis=-1)
        return arr

    return jax.tree_util.tree_map(q, params)


def quantize_params_for_plan(params, plan, min_size: int = 1024):
    """Plan-aware weight-only quantization: quantize exactly the leaves
    whose dtype role under ``plan.dtype_rules`` is ``"int8"``.

    The precision plane's serving story (docs/parallelism.md "Precision
    plane"): ``int8_serving()`` marks weights int8 in the SAME rule
    vocabulary the other three tables use, and this function is where
    the role becomes bytes.  The classic structural heuristic still
    gates each marked leaf (ndim >= 2, >= ``min_size`` elements,
    floating) — a catch-all ``.*`` int8 rule must not quantize biases
    or norm scales, matching :func:`quantize_params`.

    A plan without dtype rules (or without any int8 role) returns the
    tree unchanged — this is an overlay, not a requirement.
    """
    roles = plan.dtype_roles(params)
    if not any(r == "int8" for r in roles.values()):
        return params

    from analytics_zoo_tpu.parallel.partition import leaf_path_name

    def q(path, leaf):
        arr = jnp.asarray(leaf)
        if (roles.get(leaf_path_name(path)) == "int8"
                and arr.ndim >= 2 and arr.size >= min_size
                and jnp.issubdtype(arr.dtype, jnp.floating)):
            return _quantize_array(arr, axis=-1)
        return arr

    return jax.tree_util.tree_map_with_path(q, params)


def quantized_matmul(x, qt: QuantizedTensor):
    """``x @ dequantize(qt)`` — THE consumer for a quantized dense
    weight, kernel-plane aware.

    Under a plan whose ``kernel_rules`` route ``serving.int8_matmul``
    to the pallas kernel, a 2D last-axis-scaled weight runs the
    weight-stationary int8 MXU path
    (:func:`analytics_zoo_tpu.ops.pallas.int8_matmul.int8_matmul`):
    the weight stays 1 byte/param through HBM and VMEM instead of
    being expanded to f32 before a plain dot.  Every other case — no
    rule, an explicit ``"xla"`` pick, non-2D weights, axis-0 scales —
    is the classic dequantize-then-dot, where XLA fuses the dequant
    multiply into the consumer."""
    if isinstance(qt, QuantizedTensor) and qt.values.ndim == 2 \
            and qt.axis == qt.values.ndim - 1:
        from analytics_zoo_tpu.parallel.plan import resolve_kernel

        if resolve_kernel("serving.int8_matmul") == "int8_matmul":
            from analytics_zoo_tpu.ops.pallas.int8_matmul import (
                int8_matmul,
            )

            return int8_matmul(x, qt.values, qt.scale.reshape(-1))
    if isinstance(qt, QuantizedTensor):
        return x @ qt.dequantize(x.dtype)
    return x @ qt


def quantized_bytes_ratio(params, qparams) -> float:
    """quantized-bytes / original-bytes over the whole tree — the
    whitepaper's 4x model-size claim as a measured number (int8 values
    + f32 scales vs the float original; unquantized leaves count at
    full width on both sides)."""
    def nbytes(leaf):
        if isinstance(leaf, QuantizedTensor):
            return (np.size(leaf.values) * leaf.values.dtype.itemsize
                    + np.size(leaf.scale) * leaf.scale.dtype.itemsize)
        a = np.asarray(leaf)
        return a.size * a.dtype.itemsize

    is_qt = lambda l: isinstance(l, QuantizedTensor)  # noqa: E731
    orig = sum(nbytes(l) for l in jax.tree_util.tree_leaves(params))
    quant = sum(nbytes(l) for l in
                jax.tree_util.tree_leaves(qparams, is_leaf=is_qt))
    return float(quant) / float(orig) if orig else 1.0


def dequantize_params(params, dtype=jnp.float32):
    """Materialize a float pytree from a quantized one (device-side; XLA
    fuses the dequant multiply into each weight's consumer)."""
    def dq(leaf):
        if isinstance(leaf, QuantizedTensor):
            return leaf.dequantize(dtype)
        return leaf

    return jax.tree_util.tree_map(
        dq, params, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    )


def quantization_error(params, qparams) -> float:
    """Max relative L2 error across quantized leaves (calibration check)."""
    errs = []
    flat, _ = jax.tree_util.tree_flatten(params)
    qflat, _ = jax.tree_util.tree_flatten(
        qparams, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    )
    for a, qa in zip(flat, qflat):
        if isinstance(qa, QuantizedTensor):
            a = np.asarray(a)
            d = np.asarray(qa.dequantize())
            denom = np.linalg.norm(a)
            if denom > 0:
                errs.append(float(np.linalg.norm(a - d) / denom))
    return max(errs) if errs else 0.0


# ---------------------------------------------------------------------------
# Activation calibration + int8 x int8 execution
# ---------------------------------------------------------------------------
#
# The weight-only path above keeps activations in bf16/f32 (a bandwidth
# win).  This is the full int8 story — the role of the reference's OpenVINO
# *calibration* step (InferenceModel.scala doLoadOpenVINOInt8 with a
# calibration dataset): run representative batches, record per-layer input
# ranges, then execute Dense/Conv matmuls as int8 x int8 -> int32 on the
# MXU (2x the bf16 peak on v5e) with a single rescale to float after.


def _target_layers(net):
    from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _ConvND
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import Dense

    return [l for l in net.layers
            if isinstance(l, (Dense, _ConvND))
            and getattr(l, "name", None)]


# Serializes every apply-hook installation AND every trace that could
# observe one: instance ``apply`` methods are shared net-wide state, so a
# float trace of the same net racing an int8 install would bake the hooks
# into the wrong executable.  All installers and compilers below (and
# InferenceModel's AOT compile) hold this lock.
# zoolint: disable-file=guarded-by-candidate -- HOOK_LOCK guards foreign
# `layer.apply` attributes (swapped in _hooked), not module/class state:
# there is nothing here to annotate; lock ordering is still checked by
# the whole-program graph and the runtime sanitizer.
HOOK_LOCK = threading.RLock()


@contextmanager
def _hooked(assignments):
    """Install {layer: wrapped_apply}, restore on exit, under HOOK_LOCK."""
    originals = {}
    with HOOK_LOCK:
        try:
            for layer, wrapped in assignments.items():
                originals[layer] = layer.apply
                layer.apply = wrapped
            yield
        finally:
            for layer, orig in originals.items():
                layer.apply = orig


def calibrate_activations(net, x_batches, params=None, state=None):
    """Per-layer input abs-max over calibration batches (the reference's
    calibration dataset pass).  Eager forwards with per-instance ``apply``
    hooks; returns {layer_name: scale} where scale maps float inputs to
    int8 (amax / 127)."""
    params = params if params is not None else net.params
    state = state if state is not None else net.state
    amax: dict[str, float] = {}

    def hook(layer, orig):
        def wrapped(p, inputs, **kw):
            m = float(jnp.max(jnp.abs(inputs)))
            amax[layer.name] = max(amax.get(layer.name, 0.0), m)
            return orig(p, inputs, **kw)

        return wrapped

    assignments = {l: hook(l, l.apply) for l in _target_layers(net)}
    with _hooked(assignments):
        for xb in x_batches:
            net.forward(params, jnp.asarray(xb), state=state,
                        training=False)
    return {k: (v / 127.0 if v > 0 else 1.0) for k, v in amax.items()}


def _quantize_act(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def _int8_dense(layer, qt, act_scale, params, x):
    xs = _quantize_act(x, act_scale)
    acc = jax.lax.dot_general(
        xs, qt.values,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    w_scale = qt.scale.reshape(-1)  # per output channel
    y = acc.astype(jnp.float32) * (act_scale * w_scale)
    if layer.bias:
        y = y + params["bias"]
    return layer.activation(y)


def _int8_conv(layer, qt, act_scale, params, x):
    from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _DIMNUMS

    xs = _quantize_act(x, act_scale)
    acc = jax.lax.conv_general_dilated(
        xs, qt.values,
        window_strides=layer.subsample,
        padding=layer.border_mode.upper(),
        rhs_dilation=layer.dilation,
        dimension_numbers=_DIMNUMS[layer.rank],
        preferred_element_type=jnp.int32,
    )
    w_scale = qt.scale.reshape(-1)
    y = acc.astype(jnp.float32) * (act_scale * w_scale)
    if layer.bias:
        y = y + params["bias"]
    return layer.activation(y)


class Int8Model:
    """Calibrated int8 inference wrapper around a trained KerasNet.

    ``quantize_model(net, calib_x)`` builds one; ``predict`` runs
    Dense/Conv layers as int8 x int8 -> int32 with calibrated activation
    scales, everything else in float.  Reference role: the OpenVINO int8
    calibration pipeline (<=0.1% accuracy-drop claim, wp-bigdl.md:192).
    """

    def __init__(self, net, qparams, act_scales):
        self.net = net
        self.qparams = qparams
        self.act_scales = dict(act_scales)
        # one jitted forward for the lifetime of the wrapper: jit caches
        # by function identity, so a per-call lambda would recompile on
        # every predict
        # zoolint: disable=raw-jit -- int8 apply hooks are install-scoped trace state: the jit must trace under installed() (inference_model holds the lock), and sharing a choke-point executable cache across hook states would serve the wrong program
        self._fwd = jax.jit(lambda p, xb: self.net.forward(
            p, xb, state=self.net.state, training=False)[0])

    def _assignments(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers.core import Dense

        assignments = {}
        for layer in _target_layers(self.net):
            qt = self.qparams.get(layer.name, {}).get("kernel")
            scale = self.act_scales.get(layer.name)
            if not isinstance(qt, QuantizedTensor) or scale is None:
                continue
            kernel_fn = _int8_dense if isinstance(layer, Dense) \
                else _int8_conv

            def wrapped(p, inputs, *, _l=layer, _qt=qt, _s=scale,
                        _fn=kernel_fn, **kw):
                return _fn(_l, _qt, _s, p, inputs), kw.get("state")

            assignments[layer] = wrapped
        return assignments

    def installed(self):
        """Context manager: int8 apply hooks active (and exclusive — see
        HOOK_LOCK) for the duration; traces taken inside bake in the int8
        path."""
        return _hooked(self._assignments())

    def predict(self, x, batch_size: int = 32):
        # The hooks must be installed whenever a call might trace (any new
        # batch shape), so the whole loop runs under installed(); padding
        # the tail batch keeps the shape set to ONE executable, which also
        # bounds how long the global HOOK_LOCK is interesting to anyone.
        with self.installed():
            outs = []
            n = np.shape(x)[0]
            for i in range(0, n, batch_size):
                xb = np.asarray(x[i:i + batch_size])
                pad = batch_size - xb.shape[0]
                if pad:
                    xb = np.concatenate(
                        [xb, np.repeat(xb[-1:], pad, axis=0)], axis=0)
                out = np.asarray(self._fwd(self.qparams, jnp.asarray(xb)))
                outs.append(out[:out.shape[0] - pad] if pad else out)
            return np.concatenate(outs, axis=0)


def quantize_model(net, calib_x, batch_size: int = 32,
                   min_size: int = 1024) -> Int8Model:
    """Weight quantization + activation calibration in one step.

    calib_x: representative inputs — a single array (multi-input models
    are not calibratable yet; a few hundred samples suffice, as in the
    reference's calibration dataset).

    Only the kernels of the layers that actually get int8 execution hooks
    (top-level Dense/Conv with a calibration scale) are quantized; every
    other weight stays float, so no un-hooked layer can ever receive a
    QuantizedTensor.
    """
    if isinstance(calib_x, (list, tuple)):
        raise ValueError(
            "quantize_model: multi-input calibration is not supported; "
            "pass a single input array")
    batches = [calib_x[i:i + batch_size]
               for i in range(0, np.shape(calib_x)[0], batch_size)]
    scales = calibrate_activations(net, batches)
    hooked = {l.name for l in _target_layers(net) if l.name in scales}
    qparams = {}
    for lname, group in net.params.items():
        if lname in hooked and isinstance(group, dict) \
                and "kernel" in group:
            g = dict(group)
            k = jnp.asarray(g["kernel"])
            if k.ndim >= 2 and k.size >= min_size:
                g["kernel"] = _quantize_array(k, axis=-1)
            qparams[lname] = g
        else:
            qparams[lname] = group
    return Int8Model(net, qparams, scales)
