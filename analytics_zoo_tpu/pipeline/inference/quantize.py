"""Weight-only int8 quantization for inference.

Reference role: OpenVINO int8 calibration
(InferenceModel.scala ``doLoadOpenVINOInt8`` family;
OpenVinoInferenceSupportive.scala:33-61) with the whitepaper claim of 4x
model-size reduction at <=0.1% accuracy drop (docs/docs/wp-bigdl.md:192).

TPU-native design: per-output-channel symmetric int8 quantization of the
*parameter pytree*; activations stay bf16/f32.  Dequantization happens
on-device right before the matmul/conv, which XLA fuses into the consumer, so
HBM traffic for weights drops ~4x — the same bandwidth win the reference gets
from VNNI int8 — while the MXU still sees bf16 operands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class QuantizedTensor:
    """int8 values + per-channel float scale; a pytree leaf pair."""

    def __init__(self, values, scale, axis: int):
        self.values = values          # int8, original shape
        self.scale = scale            # f32, broadcastable to values
        self.axis = axis

    def dequantize(self, dtype=jnp.float32):
        return self.values.astype(dtype) * self.scale.astype(dtype)

    @property
    def shape(self):
        return self.values.shape

    def tree_flatten(self):
        return (self.values, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        return cls(children[0], children[1], axis)


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    QuantizedTensor.tree_flatten,
    QuantizedTensor.tree_unflatten,
)


def _quantize_array(a, axis: int) -> QuantizedTensor:
    a = jnp.asarray(a)
    reduce_axes = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
    amax = jnp.max(jnp.abs(a), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(a / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale, axis % a.ndim)


def quantize_params(params, min_size: int = 1024):
    """Quantize every large (>= min_size elements, ndim >= 2) weight to int8.

    Channel axis = last dim (dense kernels (in, out) and conv kernels
    (..., in, out) both store output channels last in this framework).
    Small tensors (biases, norms) stay in full precision — matching the
    reference's calibration behavior of only quantizing conv/FC weights.
    """
    def q(leaf):
        arr = jnp.asarray(leaf)
        if arr.ndim >= 2 and arr.size >= min_size and jnp.issubdtype(
                arr.dtype, jnp.floating):
            return _quantize_array(arr, axis=-1)
        return arr

    return jax.tree_util.tree_map(q, params)


def dequantize_params(params, dtype=jnp.float32):
    """Materialize a float pytree from a quantized one (device-side; XLA
    fuses the dequant multiply into each weight's consumer)."""
    def dq(leaf):
        if isinstance(leaf, QuantizedTensor):
            return leaf.dequantize(dtype)
        return leaf

    return jax.tree_util.tree_map(
        dq, params, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    )


def quantization_error(params, qparams) -> float:
    """Max relative L2 error across quantized leaves (calibration check)."""
    errs = []
    flat, _ = jax.tree_util.tree_flatten(params)
    qflat, _ = jax.tree_util.tree_flatten(
        qparams, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    )
    for a, qa in zip(flat, qflat):
        if isinstance(qa, QuantizedTensor):
            a = np.asarray(a)
            d = np.asarray(qa.dequantize())
            denom = np.linalg.norm(a)
            if denom > 0:
                errs.append(float(np.linalg.norm(a - d) / denom))
    return max(errs) if errs else 0.0
