"""Inference stack — TPU-native re-design of the reference's
``pipeline/inference`` (InferenceModel.scala:81-657, InferenceModelFactory,
OpenVinoInferenceSupportive) and the Java POJO surface
(AbstractInferenceModel.java).

The reference pools mutable model copies in a LinkedBlockingQueue
(InferenceModel.scala:31-73) because BigDL modules are stateful and
single-threaded.  A jitted JAX function is pure and reentrant, so the pool
here bounds *host-side concurrency* with a semaphore while one compiled XLA
executable serves all callers; the OpenVINO conversion/int8-calibration role
(OpenVinoInferenceSupportive.scala:33-61) maps to ahead-of-time lowering with
a persistent XLA compile cache plus weight-only int8 quantization.
"""

from analytics_zoo_tpu.pipeline.inference.inference_model import (
    AbstractInferenceModel,
    InferenceModel,
)
from analytics_zoo_tpu.pipeline.inference.quantize import (
    Int8Model,
    calibrate_activations,
    dequantize_params,
    quantize_model,
    quantize_params,
)

__all__ = [
    "InferenceModel",
    "AbstractInferenceModel",
    "quantize_params",
    "dequantize_params",
    "calibrate_activations",
    "quantize_model",
    "Int8Model",
]
