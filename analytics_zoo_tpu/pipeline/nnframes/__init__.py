"""nnframes — DataFrame-native ML pipeline API.

TPU re-design of the reference's Spark ML integration
(zoo/.../pipeline/nnframes/NNEstimator.scala, NNClassifier.scala,
NNImageReader.scala; python pyzoo/zoo/pipeline/nnframes/nn_classifier.py).
pandas DataFrames stand in for Spark DataFrames: the Estimator/Transformer
contract, column-based feature/label wiring, and preprocessing composition
are preserved while training funnels into the same jitted SPMD train step as
the Keras API.
"""

from analytics_zoo_tpu.pipeline.nnframes.nn_estimator import (
    NNClassifier,
    NNClassifierModel,
    NNEstimator,
    NNModel,
)
from analytics_zoo_tpu.pipeline.nnframes.nn_image_reader import NNImageReader

__all__ = [
    "NNEstimator",
    "NNModel",
    "NNClassifier",
    "NNClassifierModel",
    "NNImageReader",
]
