"""NNImageReader — image folders into DataFrames.

Reference: nnframes/NNImageReader.scala reads images into a Spark DataFrame
with an image-schema column.  Here: a pandas DataFrame with ``image``
(HWC uint8 ndarray), ``origin`` (path), ``height``/``width``/``n_channels``
columns, so nnframes estimators consume the same shape of table.
"""

from __future__ import annotations

import os

import numpy as np


class NNImageReader:
    @staticmethod
    def read_images(path: str, min_partitions: int = 1,
                    resize_h: int = -1, resize_w: int = -1):
        """Reference ``NNImageReader.readImages``; resizeH/resizeW args keep
        the reference signature (-1 = keep native size)."""
        import pandas as pd

        from analytics_zoo_tpu.feature.image.imageset import ImageSet
        from analytics_zoo_tpu.feature.image.transforms import ImageResize

        iset = ImageSet.read(path, with_label=False)
        images = iset.images
        if resize_h > 0 and resize_w > 0:
            rs = ImageResize(resize_h, resize_w)
            images = [rs(im) for im in images]
        rows = []
        for img, p in zip(images, iset.paths or [None] * len(images)):
            img = np.asarray(img)
            rows.append({
                "image": img,
                "origin": p if p is None else os.path.abspath(p),
                "height": img.shape[0],
                "width": img.shape[1],
                "n_channels": img.shape[2] if img.ndim == 3 else 1,
            })
        return pd.DataFrame(rows)

    readImages = read_images
