"""NNEstimator / NNModel / NNClassifier — DataFrame Estimator/Transformer.

Reference: nnframes/NNEstimator.scala — ``internalFit`` (:414-479) converts
``df.rdd`` to (feature, label) samples through ``samplePreprocessing``
(:382-412 ``getDataSet``), trains via InternalDistriOptimizer, and wraps the
trained net in an ``NNModel`` whose ``transform`` broadcasts the model and
appends a prediction column (:635-806).  ``NNClassifier`` /
``NNClassifierModel`` (NNClassifier.scala) specialize to classification.
Python twins: pyzoo nn_classifier.py:135 (NNEstimator), :453 (NNModel),
:513 (NNClassifier), :559 (NNClassifierModel).

Here the DataFrame is pandas, samples become a FeatureSet, and training runs
the jitted psum train step; ``transform`` runs the pooled batched jax
forward and appends the column.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing


def _col_to_array(col, preprocessing: Preprocessing | None = None):
    vals = list(col)
    if preprocessing is not None:
        vals = [preprocessing(v) for v in vals]
    arrs = [np.asarray(v, dtype=np.float32) for v in vals]
    return np.stack(arrs) if arrs and arrs[0].ndim > 0 else np.asarray(
        arrs, dtype=np.float32)


class _Params:
    """Chainable set/get param surface (Spark ML Params style, as the
    reference's ``setFeaturesCol``/``setBatchSize``/... builders)."""

    def __init__(self):
        self._features_col = "features"
        self._label_col = "label"
        self._prediction_col = "prediction"
        self._batch_size = 32
        self._max_epoch = 10

    def set_features_col(self, name):
        self._features_col = name
        return self

    def set_label_col(self, name):
        self._label_col = name
        return self

    def set_prediction_col(self, name):
        self._prediction_col = name
        return self

    def set_batch_size(self, v):
        self._batch_size = int(v)
        return self

    # camelCase aliases for parity with the py reference surface
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col
    setBatchSize = set_batch_size


class NNEstimator(_Params):
    """Trains a model on a DataFrame (reference NNEstimator.scala:198).

    Args:
      model: a KerasNet (Sequential/Model) or ZooModel.
      criterion: loss identifier or LossFunction (reference ``criterion``).
      sample_preprocessing: Preprocessing applied to each feature cell
        before stacking (reference ``samplePreprocessing``).
    """

    def __init__(self, model, criterion="mse",
                 sample_preprocessing: Preprocessing | None = None):
        super().__init__()
        from analytics_zoo_tpu.models.common import ZooModel

        self.model = model.model if isinstance(model, ZooModel) else model
        self.criterion = criterion
        self.sample_preprocessing = sample_preprocessing
        self._optim_method = "adam"
        self._validation = None        # (df, trigger) — trigger unused yet
        self._checkpoint_path = None
        self._tensorboard = None
        self._grad_clip = None

    def set_optim_method(self, optimizer):
        self._optim_method = optimizer
        return self

    def set_max_epoch(self, v):
        self._max_epoch = int(v)
        return self

    def set_validation(self, df, batch_size=None):
        """Reference ``setValidation`` (NNEstimator.scala:443-468)."""
        self._validation = df
        return self

    def set_checkpoint(self, path):
        self._checkpoint_path = path
        return self

    def set_tensorboard(self, log_dir, app_name):
        self._tensorboard = (log_dir, app_name)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        self._grad_clip = ("l2norm", float(clip_norm))
        return self

    setOptimMethod = set_optim_method
    setMaxEpoch = set_max_epoch
    setValidation = set_validation
    setCheckpoint = set_checkpoint

    def _df_to_xy(self, df):
        x = _col_to_array(df[self._features_col], self.sample_preprocessing)
        y = None
        if self._label_col in df.columns:
            # label cells keep their own shape: scalar rows -> (B,), vector
            # rows -> (B, d).  No squeezing — an (B, 1) regression target vs
            # (B,) would silently broadcast to (B, B) inside mse.
            y = _col_to_array(df[self._label_col])
        return x, y

    def fit(self, df) -> "NNModel":
        """Reference ``internalFit`` NNEstimator.scala:414-479."""
        x, y = self._df_to_xy(df)
        self.model.compile(optimizer=self._optim_method,
                           loss=self.criterion)
        if self._tensorboard:
            self.model.set_tensorboard(*self._tensorboard)
        if self._checkpoint_path:
            self.model.set_checkpoint(self._checkpoint_path)
        if self._grad_clip and self._grad_clip[0] == "l2norm":
            self.model.set_gradient_clipping_by_l2_norm(self._grad_clip[1])
        val = None
        if self._validation is not None:
            val = self._df_to_xy(self._validation)
        self.model.fit(x, y, batch_size=self._batch_size,
                       nb_epoch=self._max_epoch, validation_data=val)
        return self._wrap_model()

    def _wrap_model(self) -> "NNModel":
        """Reference ``wrapBigDLModel`` NNEstimator.scala:484-491 (clones
        the preprocessing into the transformer)."""
        m = NNModel(self.model, self.sample_preprocessing)
        m.set_features_col(self._features_col)
        m.set_prediction_col(self._prediction_col)
        m.set_batch_size(self._batch_size)
        return m


class NNModel(_Params):
    """Transformer: appends model predictions as a DataFrame column
    (reference NNModel.transform, NNEstimator.scala:635-806)."""

    def __init__(self, model, feature_preprocessing=None):
        super().__init__()
        from analytics_zoo_tpu.models.common import ZooModel

        self.model = model.model if isinstance(model, ZooModel) else model
        self.feature_preprocessing = feature_preprocessing

    def _predict_array(self, df) -> np.ndarray:
        x = _col_to_array(df[self._features_col],
                          self.feature_preprocessing)
        return self.model.predict(x, batch_size=self._batch_size)

    def transform(self, df):
        out = self._predict_array(df)
        df = df.copy()
        df[self._prediction_col] = [np.asarray(row) for row in out]
        return df


class NNClassifier(NNEstimator):
    """Classification sugar (reference NNClassifier.scala; py
    nn_classifier.py:513): sparse-categorical criterion by default, model
    wrapped as NNClassifierModel emitting class labels."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 sample_preprocessing=None):
        super().__init__(model, criterion, sample_preprocessing)

    def _wrap_model(self):
        m = NNClassifierModel(self.model, self.sample_preprocessing)
        m.set_features_col(self._features_col)
        m.set_prediction_col(self._prediction_col)
        m.set_batch_size(self._batch_size)
        return m


class NNClassifierModel(NNModel):
    """Reference NNClassifierModel (nn_classifier.py:559): prediction column
    holds the argmax class index (float, matching Spark ML convention)."""

    def transform(self, df):
        probs = self._predict_array(df)
        df = df.copy()
        df[self._prediction_col] = np.argmax(probs, axis=-1).astype(
            np.float64)
        return df
