"""LocalEstimator — single-device trainer without a mesh.

Reference: zoo/.../pipeline/estimator/LocalEstimator.scala:39-211, a
thread-pool trainer that bypasses Spark (`fit` with parallel forward/backward
via ThreadPool.invokeAndWait :178-199).  The TPU analogue of "no cluster" is
"no mesh": one jit-compiled step on the default device.  The thread-pool
replica parallelism collapses into XLA's own intra-chip parallelism.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.feature.dataset import FeatureSet
from analytics_zoo_tpu.pipeline.api.keras.metrics import get_metric
from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
from analytics_zoo_tpu.pipeline.api.keras.optimizers import get_optimizer


class LocalEstimator:
    def __init__(self, model, criterion, optimizer, metrics=None):
        self.model = model
        self.loss = get_loss(criterion)
        self.optimizer = get_optimizer(optimizer)
        self.metrics = [get_metric(m) for m in (metrics or [])]

    def fit(self, x, y, validation_data=None, batch_size=32, epochs=1,
            seed=0, steps_per_dispatch=None):
        """``steps_per_dispatch=K>1`` (default: ``ZOO_STEPS_PER_DISPATCH``)
        fuses K train steps into one jitted ``lax.scan`` dispatch — the
        single-device twin of the Estimator's fused path, with the same
        contract: per-step RNG folds on the global iteration index, so
        the loss trajectory is bit-identical to K=1; a partial tail chunk
        falls back to single steps."""
        model, loss_fn, opt = self.model, self.loss, self.optimizer
        if steps_per_dispatch is None:
            steps_per_dispatch = int(
                os.environ.get("ZOO_STEPS_PER_DISPATCH", "1"))
        k = int(steps_per_dispatch)
        if k < 1:
            # same contract as ZooConfig.__post_init__: a misconfigured
            # knob fails loudly on every entry point, never clamps
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        params, state = model.build_params()
        opt_state = opt.init(params)

        def one_step(params, opt_state, state, rng, bx, by):
            def loss_of(p):
                preds, new_state = model.forward(p, bx, state=state,
                                                 training=True, rng=rng)
                return loss_fn.mean(by, preds), new_state

            (l, new_state), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_state, l

        # through the unified partitioner's choke point (no mesh → the
        # default replicate-everything plan): the local trainer shares
        # the persistent compile cache / metering / HLO lint with the
        # distributed estimator
        from analytics_zoo_tpu.parallel.plan import compile_step

        def step_fn(params, opt_state, state, rng, bx, by):
            return one_step(params, opt_state, state, rng, bx, by)

        step = compile_step(step_fn, donate_argnums=(0, 1, 2),
                            label="local_step")

        def step_scan_fn(params, opt_state, state, it0, sbx, sby):
            key = jax.random.PRNGKey(seed)

            def body(carry, xs):
                p, o, s = carry
                bx, by, i = xs
                p, o, s, l = one_step(p, o, s,
                                      jax.random.fold_in(key, it0 + i),
                                      bx, by)
                return (p, o, s), l

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (sbx, sby, jnp.arange(k, dtype=jnp.int32)))
            return params, opt_state, state, losses[-1]

        step_scan = compile_step(step_scan_fn, donate_argnums=(0, 1, 2),
                                 label=f"local_step_scan{k}")

        from analytics_zoo_tpu.pipeline.estimator.estimator import (
            _chunk_batches,
        )

        fs = FeatureSet.of(x, y)
        it = 0
        history = []
        for epoch in range(epochs):
            last = None
            batches = fs.batches(batch_size, shuffle=True, seed=seed,
                                 epoch=epoch)
            # the estimator's chunker (full chunks fused, tail degrades
            # to single steps); at K=1 the stream is consumed directly
            items = (("single", b) for b in batches) if k == 1 \
                else _chunk_batches(batches, k)
            for kind, payload in items:
                if kind == "scan":
                    params, opt_state, state, last = step_scan(
                        params, opt_state, state, jnp.int32(it),
                        jnp.asarray(np.stack([b["x"] for b in payload])),
                        jnp.asarray(np.stack([b["y"] for b in payload])),
                    )
                    it += k
                else:
                    rng = jax.random.fold_in(jax.random.PRNGKey(seed), it)
                    params, opt_state, state, last = step(
                        params, opt_state, state, rng,
                        jnp.asarray(payload["x"]),
                        jnp.asarray(payload["y"]),
                    )
                    it += 1
            history.append(float(last) if last is not None else None)
        model.params, model.state = params, state
        self.history = history
        return self

    def evaluate(self, x, y, batch_size=32):
        from analytics_zoo_tpu.parallel.plan import compile_step

        model = self.model
        params, state = model.build_params()

        def fwd_fn(params, state, bx):
            return model.forward(params, bx, state=state, training=False)[0]

        fwd = compile_step(fwd_fn, label="local_eval")

        fs = FeatureSet.of(x, y)
        accums = [None] * (len(self.metrics) + 1)
        for batch in fs.batches(batch_size, shuffle=False, drop_last=False):
            preds = fwd(params, state, jnp.asarray(batch["x"]))
            by = jnp.asarray(batch["y"])
            per = self.loss(by, preds)
            stats = [(jnp.sum(per), jnp.asarray(per.shape[0], jnp.float32))]
            stats += [m.batch_stats(by, preds) for m in self.metrics]
            for i, s in enumerate(stats):
                host = [np.asarray(v) for v in s]
                accums[i] = host if accums[i] is None else [
                    a + b for a, b in zip(accums[i], host)
                ]
        out = {"loss": float(accums[0][0]) / max(float(accums[0][1]), 1e-12)}
        for m, acc in zip(self.metrics, accums[1:]):
            out[m.name] = m.finalize(acc)
        return out
