from analytics_zoo_tpu.pipeline.estimator.estimator import (  # noqa: F401
    Estimator,
)
from analytics_zoo_tpu.pipeline.estimator.local import (  # noqa: F401
    LocalEstimator,
)
